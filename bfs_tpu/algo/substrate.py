"""The semiring substrate: one superstep machine, many graph algorithms.

ROADMAP item 4's observation, made executable: nothing in the superstep
machinery is BFS-specific.  Every level-synchronous engine in this repo
is the same three-phase loop

    contribute  — per active edge, a value derived from source state;
    combine     — one segmented min over edge destinations
                  (:func:`bfs_tpu.ops.relax.combine_min`);
    apply       — merge candidates into per-vertex state, the improved
                  set becomes the next frontier, termination is
                  "nothing improved".

parameterized by a ``(contribute, combine, identity, state)`` tuple — a
commutative selection semiring, exactly the tensor-core generalization of
"Graph Traversal on Tensor Cores" (arxiv 2606.05081) and BLEST (arxiv
2512.21967).  :data:`SEMIRINGS` is the contract table (mirrored in
docs/ARCHITECTURE.md §24); the algorithm modules (:mod:`bfs_tpu.algo.sssp`,
:mod:`bfs_tpu.algo.cc`) instantiate it on the existing fused / segmented /
sharded program families.

This module also owns the two pieces the algorithms share:

  * :func:`edge_weights_np` / ``edge_weights`` — deterministic per-edge
    weights as a HASH of the endpoints, not a parallel array that must be
    permuted alongside every relayout.  ``w(u, v) = f(u, v)`` survives
    dst-sorting, sentinel padding and round-robin sharding with zero
    plumbing: any engine recomputes its shard's weights from the edge
    arrays it already holds (the sharded programs do it inside the
    ``shard_map`` body), and the host oracle recomputes the identical
    values from the host edge list.
  * :func:`drive_segments` — the generic segmented-traversal driver over
    :class:`~bfs_tpu.resilience.superstep_ckpt.SuperstepCheckpointer`:
    bounded device segments, a durable epoch per boundary, the
    ``superstep:<n>`` fault family, and the shared restore gate — so
    SSSP / CC kill/resume rides the exact contract PR 14 built for BFS.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import numpy as np

from .. import knobs
from ..ops.relax import INT32_MAX

# --------------------------------------------------------------- contract --

@dataclass(frozen=True)
class Semiring:
    """One row of the semiring contract table (docs/ARCHITECTURE.md §24).

    ``contribute`` / ``combine`` are documentation strings — the actual
    math lives in the algorithm modules, routed through
    :func:`~bfs_tpu.ops.relax.combine_min` — plus the two capability bits
    the engine matrix branches on: ``packable`` (is there a fused-word
    carry?) and ``mxu_eligible`` (can frontier expansion run as the PR 15
    bit-packed masked matmul? only boolean-mask contributions can; valued
    contributions like min-plus sums cannot ride an AND/popcount tile).
    """

    name: str
    contribute: str
    combine: str
    identity: int
    state: tuple
    packable: bool
    mxu_eligible: bool


#: name -> contract row.  The engine matrix each algorithm ships on is
#: documented per algorithm module; this table is the shared vocabulary.
SEMIRINGS = {
    "bfs": Semiring(
        name="bfs",
        contribute="src if frontier[src]",
        combine="segment_min over dst",
        identity=int(INT32_MAX),
        state=("dist", "parent", "frontier"),
        packable=True,  # level:6|parent:26 (ops/packed.py)
        mxu_eligible=True,  # boolean masks: AND/popcount tiles (PR 15)
    ),
    "sssp": Semiring(
        name="sssp",
        contribute="dist[src] + w(src, dst) if frontier[src]",
        combine="segment_min over dst",
        identity=int(INT32_MAX),
        state=("dist", "dirty", "threshold"),
        packable=True,  # dist:16|parent:16 (algo/sssp.py, V < 2^16-1)
        mxu_eligible=False,  # valued contributions: no popcount encoding
    ),
    "cc": Semiring(
        name="cc",
        contribute="label[src] if frontier[src]",
        combine="segment_min over dst",
        identity=int(INT32_MAX),
        state=("label", "frontier"),
        packable=False,  # label IS the whole word already
        mxu_eligible=False,  # label values, not boolean masks
    ),
}


# ---------------------------------------------------------------- weights --
# 32-bit multiply-xorshift mix (splitmix-style finalizer constants).  The
# ONLY requirement is determinism as a pure function of (src, dst) with a
# well-spread low-bit distribution; uint32 wraparound is defined in both
# numpy array ops and XLA, so host and device values agree bit-for-bit.

_W_C1 = 0x9E3779B1
_W_C2 = 0x85EBCA77
_W_C3 = 0x7FEB352D

#: Default weight range [1, DEFAULT_MAX_WEIGHT].  255 matches the byte
#: weights of the Graph500 SSSP reference generator's integer variant.
DEFAULT_MAX_WEIGHT = 255


def edge_weights_np(src, dst, max_weight: int = DEFAULT_MAX_WEIGHT):
    """Host twin of :func:`edge_weights`: int32 weights in
    ``[1, max_weight]`` for each directed edge, bit-identical to the
    traced version (the oracle runs on these)."""
    if max_weight < 1:
        raise ValueError("max_weight must be >= 1")
    s = np.asarray(src).astype(np.uint32)
    d = np.asarray(dst).astype(np.uint32)
    h = s * np.uint32(_W_C1) + d * np.uint32(_W_C2)
    h ^= h >> np.uint32(16)
    h *= np.uint32(_W_C3)
    h ^= h >> np.uint32(15)
    return (np.uint32(1) + h % np.uint32(max_weight)).astype(np.int32)


# bfs_tpu: hot traced
def edge_weights(src, dst, max_weight: int):
    """Traced weights-from-endpoints: recomputed wherever the edge arrays
    already live (fused programs once per trace, sharded programs inside
    the mesh body) instead of shipped as a parallel operand that every
    relayout/reshard would have to permute in lockstep."""
    import jax.numpy as jnp

    s = src.astype(jnp.uint32)
    d = dst.astype(jnp.uint32)
    h = s * jnp.uint32(_W_C1) + d * jnp.uint32(_W_C2)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_W_C3)
    h = h ^ (h >> 15)
    return (jnp.uint32(1) + h % jnp.uint32(max_weight)).astype(jnp.int32)


# ------------------------------------------------------------ delta knob --

def resolve_delta(delta: int | str | None = None) -> int:
    """The delta-stepping bucket width: explicit argument, else
    ``BFS_TPU_SSSP_DELTA`` (int, or ``inf`` for one bucket = plain
    frontier Bellman-Ford), else 64 — about half the default mean weight,
    the classic delta ~ w_mean starting point.  Returned as the int32
    threshold increment (``inf`` maps to INT32_MAX: the first bucket
    already spans every finite distance)."""
    if delta is None:
        delta = knobs.get("BFS_TPU_SSSP_DELTA")
    if isinstance(delta, str):
        if delta.lower() in ("inf", "infinite", "single"):
            return int(INT32_MAX)
        delta = int(delta)
    if delta <= 0:
        return int(INT32_MAX)
    return min(int(delta), int(INT32_MAX))


# ------------------------------------------------------ segmented driver --

def drive_segments(ckpt, *, init, seg, fields, packed: bool, cap: int):
    """The generic segmented-traversal loop every algo engine shares.

    ``init(restore_arrays_or_None)`` builds the device carry (possibly
    resuming); ``seg(carry, seg_end)`` runs one bounded device segment;
    ``fields`` are the carry's field names (the restore gate's required
    keys); ``cap`` bounds total rounds.  The carry must expose ``rounds``
    (int32 scalar, monotone per superstep) and ``changed`` (bool scalar,
    work remains).  Returns ``(carry, rounds, changed)``.

    Epoch snapshots carry every field plus ``packed_flag`` — the same
    restore-gate contract as the BFS drivers
    (:func:`bfs_tpu.resilience.superstep_ckpt.restore_arrays`), so a
    flavor mismatch or a missing key falls back to a fresh traversal,
    never a mid-restore KeyError.  ``save_epoch`` marks the
    ``superstep:<n>`` fault boundary even with the store disabled, so
    chaos schedules target algo traversals unchanged."""
    import jax
    import jax.numpy as jnp

    from ..resilience.superstep_ckpt import restore_arrays

    arrays, _shards = restore_arrays(ckpt, packed, require=fields)
    carry = init(arrays)
    rounds, changed = jax.device_get((carry.rounds, carry.changed))
    while bool(changed) and int(rounds) < cap:
        k = ckpt.interval()
        seg_end = jnp.int32(min(int(rounds) + k, cap))
        t0 = time.perf_counter()
        carry = seg(carry, seg_end)
        new_rounds, changed = jax.device_get((carry.rounds, carry.changed))
        seg_s = time.perf_counter() - t0
        snap = {}
        if ckpt.enabled:
            snap = {
                name: np.asarray(val)
                for name, val in jax.device_get(carry)._asdict().items()
            }
            snap["packed_flag"] = np.int32(packed)
        ckpt.save_epoch(int(new_rounds), snap)
        ckpt.note_segment(int(new_rounds) - int(rounds), seg_s)
        rounds = new_rounds
    return carry, int(rounds), bool(changed)
