"""Weighted SSSP as min-plus supersteps with delta-stepping buckets.

The BFS superstep machinery instantiated on the ``sssp`` semiring row
(:data:`bfs_tpu.algo.substrate.SEMIRINGS`): per active edge the
contribution is ``dist[src] + w(src, dst)`` instead of ``src``, the
combine is the SAME segmented min (:func:`bfs_tpu.ops.relax.combine_min`),
and the apply keeps the min per destination.  Because weights are a hash
of the endpoints (:func:`bfs_tpu.algo.substrate.edge_weights`), every
layout — dst-sorted, padded, round-robin sharded — recomputes its own
weights from the edge arrays it already holds.

**Delta-stepping.**  The loop carry includes a bucket ``threshold`` T:
only dirty vertices with ``dist < T`` relax (the current bucket).  When
the bucket drains with dirty work remaining, T jumps to
``min(dist[dirty]) + delta`` — the classic bucket advance, here one
``where`` on a carried scalar, no host round-trip.  ``delta=inf`` (env
``BFS_TPU_SSSP_DELTA``) degenerates to one bucket = plain frontier
Bellman-Ford; any delta yields the same fixpoint (tests pin this), it
only reshapes the superstep schedule, trading rounds against wasted
long-edge relaxations exactly as in the CPU algorithm.

**Canonical parents.**  Parents are NOT carried through the loop: the
unique shortest-distance fixpoint determines them after the fact.  One
exit-time canonicalization pass (:func:`_sssp_parents`) takes, per
reached vertex, the MINIMUM u among in-edges with
``dist[u] + w(u, v) == dist[v]`` — the same ``combine_min`` — so every
engine arm (fused, segmented, sharded, packed) produces bit-identical
parents, and the host Dijkstra oracle applies the identical rule.

**Packed arm.**  For ``V < 2^16 - 1`` the carry word fuses
``dist:16 | parent:16`` (the BFS ``level:6|parent:26`` word widened for
valued distances): candidates travel as packed words through ONE uint32
``segment_min`` and the merge is gated on STRICT distance improvement, so
the packed arm's frontier schedule and round count are bit-identical to
the unpacked arm.  Distances are clamped to 0xFFFE in flight; a final
distance hitting the clamp reports truncation and the caller re-runs
unpacked — the same detect-and-fall-back contract as the >62-level BFS
cap (``packed_truncated``).  The word's parent bits are a provisional
last-improver (diagnostic only); the exit canonicalization pass is
authoritative on both arms.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import traced
from ..graph.csr import Graph, NO_PARENT, build_device_graph
from ..ops.relax import INT32_MAX, combine_min
from .substrate import DEFAULT_MAX_WEIGHT, edge_weights, resolve_delta

#: Host-int mirror of the unreached sentinel for static saturation
#: arithmetic inside the supersteps (INT32_MAX itself is np.int32).
_INT32_MAX_HOST = int(INT32_MAX)

#: Packed-arm capacity: dist field holds [0, 0xFFFD]; 0xFFFE is the
#: in-flight clamp (truncation canary), 0xFFFF the unreached sentinel.
PACKED16_DIST_CLAMP = 0xFFFE
PACKED16_UNREACHED = 0xFFFF
#: Parent field capacity: ids in [0, V] with 0xFFFF = no parent, so the
#: packed arm requires V < 0xFFFF.
PACKED16_MAX_V = 0xFFFF


def packed16_fits(num_vertices: int) -> bool:
    """True when the dist:16|parent:16 carry can represent this graph."""
    return int(num_vertices) < PACKED16_MAX_V


class SsspState(NamedTuple):
    """Unpacked loop carry.  ``dirty`` marks vertices whose dist improved
    since they last relaxed their out-edges (the delta-stepping work
    set); ``threshold`` is the current bucket's exclusive upper bound."""

    dist: jax.Array  # int32[V+1]; INT32_MAX = unreached; slot V inert
    dirty: jax.Array  # bool[V+1]
    threshold: jax.Array  # int32 scalar
    rounds: jax.Array  # int32 scalar: supersteps executed
    changed: jax.Array  # bool scalar: dirty work remains


class PackedSsspState(NamedTuple):
    """Packed twin: ``packed`` is uint32[V+1] ``dist:16|parent:16``
    (all-ones = unreached); other fields as in :class:`SsspState`."""

    packed: jax.Array  # uint32[V+1]
    dirty: jax.Array  # bool[V+1]
    threshold: jax.Array  # int32 scalar
    rounds: jax.Array
    changed: jax.Array


def init_sssp_state(num_vertices: int, source, delta: int) -> SsspState:
    n = num_vertices + 1
    source = jnp.asarray(source, dtype=jnp.int32)
    dist = jnp.full((n,), INT32_MAX, dtype=jnp.int32).at[source].set(0)
    dirty = jnp.zeros((n,), dtype=bool).at[source].set(True)
    return SsspState(
        dist, dirty, jnp.int32(delta), jnp.int32(0), jnp.bool_(True)
    )


def init_packed_sssp_state(
    num_vertices: int, source, delta: int
) -> PackedSsspState:
    n = num_vertices + 1
    source = jnp.asarray(source, dtype=jnp.int32)
    # Source word: dist 0, parent = itself.
    packed = (
        jnp.full((n,), 0xFFFFFFFF, dtype=jnp.uint32)
        .at[source]
        .set(source.astype(jnp.uint32))
    )
    dirty = jnp.zeros((n,), dtype=bool).at[source].set(True)
    return PackedSsspState(
        packed, dirty, jnp.int32(delta), jnp.int32(0), jnp.bool_(True)
    )


def packed16_dist(packed: jax.Array) -> jax.Array:
    """int32 distances from packed words (0xFFFF -> INT32_MAX)."""
    d16 = (packed >> 16).astype(jnp.int32)
    return jnp.where(d16 == PACKED16_UNREACHED, INT32_MAX, d16)


# bfs_tpu: hot traced
def sssp_superstep(
    state: SsspState,
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    delta: int,
    *,
    axis_name: str | None = None,
) -> SsspState:
    """One min-plus superstep: relax the current bucket's dirty vertices,
    then advance the bucket threshold iff it drained with work left.

    With ``axis_name``, ``src``/``dst``/``w`` are this device's edge
    shard and candidates merge across the mesh with ``lax.pmin`` — the
    identical collective shape as the BFS sharded superstep, so the
    min-plus arm inherits the replicated-state contract unchanged."""
    n = state.dist.shape[0]
    frontier = state.dirty & (state.dist < state.threshold)
    active = frontier[src]
    # The sum may wrap where inactive (dist = INT32_MAX); those lanes are
    # masked to the identity before the combine ever sees them.
    sums = state.dist[src] + w
    cand = combine_min(jnp.where(active, sums, INT32_MAX), dst, n)
    if axis_name is not None:
        cand = jax.lax.pmin(cand, axis_name)
    improved = cand < state.dist
    dist = jnp.where(improved, cand, state.dist)
    dirty = (state.dirty & ~frontier) | improved
    # Bucket advance: only when the bucket drained (no frontier at all)
    # and dirty work remains beyond the threshold.
    min_dirty = jnp.min(jnp.where(dirty, dist, INT32_MAX))
    # Saturating advance: min(.., MAX-delta)+delta keeps the final
    # all-buckets threshold finite (delta=inf lands exactly on INT32_MAX).
    threshold = jnp.where(
        ~frontier.any() & (min_dirty != INT32_MAX),
        jnp.minimum(min_dirty, jnp.int32(_INT32_MAX_HOST - delta))
        + jnp.int32(delta),
        state.threshold,
    )
    return SsspState(
        dist, dirty, threshold, state.rounds + 1, dirty.any()
    )


# bfs_tpu: hot traced
def sssp_superstep_packed(
    state: PackedSsspState,
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    delta: int,
    *,
    axis_name: str | None = None,
) -> PackedSsspState:
    """Packed twin: candidates travel as ``dist:16|parent:16`` words
    through one uint32 combine; the merge is strict on the DISTANCE field
    so the frontier schedule is bit-identical to the unpacked arm."""
    n = state.packed.shape[0]
    d16 = state.packed >> 16  # uint32; 0xFFFF = unreached
    frontier = state.dirty & (
        d16.astype(jnp.int32) < state.threshold
    ) & (d16 != PACKED16_UNREACHED)
    active = frontier[src]
    sums = jnp.minimum(
        d16[src] + w.astype(jnp.uint32), jnp.uint32(PACKED16_DIST_CLAMP)
    )
    cand_word = (sums << 16) | src.astype(jnp.uint32)
    cand = combine_min(
        jnp.where(active, cand_word, jnp.uint32(0xFFFFFFFF)), dst, n
    )
    if axis_name is not None:
        cand = jax.lax.pmin(cand, axis_name)
    improved = (cand >> 16) < d16
    packed = jnp.where(improved, cand, state.packed)
    dirty = (state.dirty & ~frontier) | improved
    new_d16 = packed >> 16
    dirty_dist = jnp.where(
        dirty & (new_d16 != PACKED16_UNREACHED),
        new_d16.astype(jnp.int32),
        INT32_MAX,
    )
    min_dirty = jnp.min(dirty_dist)
    threshold = jnp.where(
        ~frontier.any() & (min_dirty != INT32_MAX),
        jnp.minimum(min_dirty, jnp.int32(_INT32_MAX_HOST - delta))
        + jnp.int32(delta),
        state.threshold,
    )
    return PackedSsspState(
        packed, dirty, threshold, state.rounds + 1, dirty.any()
    )


@functools.partial(jax.jit, static_argnames=("num_segments", "max_weight"))
@traced("algo.sssp_parents")
def _sssp_parents(dist, src, dst, source, num_segments: int, max_weight: int):
    """Exit-time canonicalization: per reached non-source vertex, parent =
    MIN u over in-edges with ``dist[u] + w(u, v) == dist[v]`` — the same
    combine, one pass, identical on every arm.  Every optimal predecessor
    qualifies (its dist is final), so this is the global canonical
    tie-break, not a schedule artifact."""
    w = edge_weights(src, dst, max_weight)
    ds = dist[src]
    ok = (ds != INT32_MAX) & (ds + w == dist[dst])
    parent = combine_min(
        jnp.where(ok, src, INT32_MAX), dst, num_segments
    )
    reached = dist != INT32_MAX
    parent = jnp.where(
        reached & (parent != INT32_MAX), parent, jnp.int32(NO_PARENT)
    )
    return parent.at[source].set(jnp.asarray(source, jnp.int32))


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_vertices", "max_weight", "delta", "max_rounds", "packed",
    ),
)
@traced("algo.sssp_fused")
def _sssp_fused(
    src,
    dst,
    source,
    num_vertices: int,
    max_weight: int,
    delta: int,
    max_rounds: int,
    packed: bool = False,
):
    """The fused SSSP program: weights from the endpoint hash, then one
    ``while_loop`` of min-plus supersteps (packed or unpacked carry)."""
    w = edge_weights(src, dst, max_weight)
    if packed:
        pstate = init_packed_sssp_state(num_vertices, source, delta)

        def pcond(s):
            return s.changed & (s.rounds < max_rounds)

        def pbody(s):
            return sssp_superstep_packed(s, src, dst, w, delta)

        return jax.lax.while_loop(pcond, pbody, pstate)
    state = init_sssp_state(num_vertices, source, delta)

    def cond(s):
        return s.changed & (s.rounds < max_rounds)

    def body(s):
        return sssp_superstep(s, src, dst, w, delta)

    return jax.lax.while_loop(cond, body, state)


@functools.partial(
    jax.jit,
    static_argnames=("num_vertices", "max_weight", "delta", "packed"),
    donate_argnums=(0,),
)
@traced("algo.sssp_segment")
def _sssp_segment(
    state,
    seg_end,
    src,
    dst,
    num_vertices: int,
    max_weight: int,
    delta: int,
    packed: bool = False,
):
    """ONE bounded segment of the fused loop — the checkpointable twin.
    ``seg_end`` is a TRACED round bound: advancing it costs no retrace,
    and a sequence of segments runs exactly the supersteps the fused
    program would (bit-identical carries at every boundary)."""
    w = edge_weights(src, dst, max_weight)

    def cond(s):
        return s.changed & (s.rounds < seg_end)

    if packed:

        def pbody(s):
            return sssp_superstep_packed(s, src, dst, w, delta)

        return jax.lax.while_loop(cond, pbody, state)

    def body(s):
        return sssp_superstep(s, src, dst, w, delta)

    return jax.lax.while_loop(cond, body, state)


# ------------------------------------------------------------ host driver --

@dataclass
class SsspResult:
    """Host-side result in the oracle's shapes: int32[V] ``dist``
    (INT32_MAX = unreached) and canonical int32[V] ``parent`` (sentinel
    slot stripped).  ``rounds`` counts executed supersteps including
    bucket-advance rounds; ``packed`` reports the carry flavor that
    PRODUCED the result (False after a truncation fallback)."""

    dist: np.ndarray
    parent: np.ndarray
    rounds: int
    max_weight: int
    delta: int
    packed: bool
    truncated_fallbacks: int = 0

    def dist_to(self, v: int) -> int:
        return int(self.dist[v])

    def has_path_to(self, v: int) -> bool:
        return int(self.dist[v]) != int(INT32_MAX)


def _rounds_cap(num_vertices: int, max_weight: int, max_rounds) -> int:
    """Safety bound on supersteps: within a bucket each round extends the
    settled distance prefix by >= 1 weight unit (integer weights >= 1),
    and each advance covers >= 1 dirty vertex — so total rounds are
    bounded by max finite distance + bucket count, <= (w_max + 1) * V.
    The loop exits on convergence long before this on any real graph."""
    if max_rounds is not None:
        return int(max_rounds)
    return (int(max_weight) + 1) * (int(num_vertices) + 1)


def _finish(dist_dev, src_dev, dst_dev, source, n, max_weight):
    dist = np.asarray(jax.device_get(dist_dev))
    parent = np.asarray(
        jax.device_get(
            _sssp_parents(
                dist_dev, src_dev, dst_dev, jnp.int32(source), n, max_weight
            )
        )
    )
    return dist, parent


def resolve_packed16(num_vertices: int) -> bool:
    """``BFS_TPU_PACKED=0/1`` forces the carry flavor (the same knob as
    BFS); otherwise packed exactly when dist:16|parent:16 fits."""
    from ..ops.packed import resolve_packed

    return resolve_packed(packed16_fits(num_vertices))


def sssp(
    graph: Graph,
    source: int = 0,
    *,
    max_weight: int = DEFAULT_MAX_WEIGHT,
    delta: int | str | None = None,
    max_rounds: int | None = None,
    packed: bool | None = None,
    block: int = 1024,
) -> SsspResult:
    """Single-source shortest paths on the fused push engine.

    Weights are ``edge_weights(src, dst, max_weight)`` — pass the same
    ``max_weight`` to :func:`bfs_tpu.oracle.sssp.dijkstra` (with
    :func:`bfs_tpu.algo.substrate.edge_weights_np`) for oracle parity.
    ``packed=None`` resolves the dist:16|parent:16 arm automatically and
    falls back unpacked when a final distance hits the 16-bit clamp."""
    dg = build_device_graph(graph, block=block)
    return sssp_device(
        jnp.asarray(dg.src), jnp.asarray(dg.dst), dg.num_vertices, source,
        max_weight=max_weight, delta=delta, max_rounds=max_rounds,
        packed=packed,
    )


def sssp_device(
    src_dev,
    dst_dev,
    num_vertices: int,
    source: int = 0,
    *,
    max_weight: int = DEFAULT_MAX_WEIGHT,
    delta: int | str | None = None,
    max_rounds: int | None = None,
    packed: bool | None = None,
) -> SsspResult:
    """:func:`sssp` against ALREADY-RESIDENT sentinel-padded device edge
    arrays — the form the serve registry's residency layer feeds
    (:func:`bfs_tpu.serve.algo.registry_sssp`): operands upload once per
    (graph, engine) epoch and every traversal reuses them."""
    v = int(num_vertices)
    n = v + 1
    delta_i = resolve_delta(delta)
    cap = _rounds_cap(v, max_weight, max_rounds)
    use_packed = (
        resolve_packed16(v) if packed is None else bool(packed)
    )
    fallbacks = 0
    if use_packed and not packed16_fits(v):
        raise ValueError(
            f"packed16 carry needs V < {PACKED16_MAX_V}, got {v}"
        )
    if use_packed:
        pstate = _sssp_fused(
            src_dev, dst_dev, jnp.int32(source),
            num_vertices=v, max_weight=max_weight, delta=delta_i,
            max_rounds=cap, packed=True,
        )
        if not bool(jax.device_get(packed16_truncated(pstate.packed))):
            dist_dev = packed16_dist(pstate.packed)
            dist, parent = _finish(
                dist_dev, src_dev, dst_dev, source, n, max_weight
            )
            return SsspResult(
                dist=dist[:v], parent=parent[:v],
                rounds=int(jax.device_get(pstate.rounds)),
                max_weight=max_weight, delta=delta_i, packed=True,
            )
        fallbacks = 1  # clamp hit: the packed dists are not trustworthy
    state = _sssp_fused(
        src_dev, dst_dev, jnp.int32(source),
        num_vertices=v, max_weight=max_weight, delta=delta_i,
        max_rounds=cap, packed=False,
    )
    dist, parent = _finish(
        state.dist, src_dev, dst_dev, source, n, max_weight
    )
    return SsspResult(
        dist=dist[:v], parent=parent[:v],
        rounds=int(jax.device_get(state.rounds)),
        max_weight=max_weight, delta=delta_i, packed=False,
        truncated_fallbacks=fallbacks,
    )


@functools.partial(jax.jit)
@traced("algo.sssp_truncated")
def packed16_truncated(packed) -> jax.Array:
    """Did any final packed distance hit the in-flight clamp?  The clamp
    value doubles as the truncation canary: a genuine distance of exactly
    0xFFFE also reports truncation (conservative — the unpacked re-run is
    correct either way)."""
    return ((packed >> 16) == PACKED16_DIST_CLAMP).any()


def sssp_segmented(
    graph: Graph,
    source: int = 0,
    *,
    ckpt,
    max_weight: int = DEFAULT_MAX_WEIGHT,
    delta: int | str | None = None,
    max_rounds: int | None = None,
    packed: bool | None = None,
    block: int = 1024,
) -> SsspResult:
    """Checkpointed twin of :func:`sssp`: the fused loop cut into bounded
    segments with a durable epoch per boundary
    (:func:`bfs_tpu.algo.substrate.drive_segments`) — bit-identical
    results for any segmentation, kill/resume included."""
    from .substrate import drive_segments

    dg = build_device_graph(graph, block=block)
    v = dg.num_vertices
    n = v + 1
    delta_i = resolve_delta(delta)
    cap = _rounds_cap(v, max_weight, max_rounds)
    src_dev, dst_dev = jnp.asarray(dg.src), jnp.asarray(dg.dst)

    def run_flavor(use_packed: bool):
        cls = PackedSsspState if use_packed else SsspState

        def init(arrays):
            if arrays is not None:
                return cls(**{
                    k: jnp.asarray(arrays[k]) for k in cls._fields
                })
            if use_packed:
                return init_packed_sssp_state(v, source, delta_i)
            return init_sssp_state(v, source, delta_i)

        def seg(carry, seg_end):
            return _sssp_segment(
                carry, seg_end, src_dev, dst_dev,
                num_vertices=v, max_weight=max_weight, delta=delta_i,
                packed=use_packed,
            )

        return drive_segments(
            ckpt, init=init, seg=seg, fields=cls._fields,
            packed=use_packed, cap=cap,
        )

    use_packed = resolve_packed16(v) if packed is None else bool(packed)
    fallbacks = 0
    if use_packed:
        pstate, rounds, _ = run_flavor(True)
        if not bool(jax.device_get(packed16_truncated(pstate.packed))):
            dist, parent = _finish(
                packed16_dist(pstate.packed), src_dev, dst_dev, source,
                n, max_weight,
            )
            ckpt.clear()
            return SsspResult(
                dist=dist[:v], parent=parent[:v], rounds=rounds,
                max_weight=max_weight, delta=delta_i, packed=True,
            )
        fallbacks = 1
        ckpt.clear()  # packed epochs cannot feed the unpacked re-run
    state, rounds, _ = run_flavor(False)
    dist, parent = _finish(
        state.dist, src_dev, dst_dev, source, n, max_weight
    )
    ckpt.clear()
    return SsspResult(
        dist=dist[:v], parent=parent[:v], rounds=rounds,
        max_weight=max_weight, delta=delta_i, packed=False,
        truncated_fallbacks=fallbacks,
    )
