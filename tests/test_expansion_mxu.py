"""MXU expansion arm (ISSUE 15): tile-layout builder parity, kernel/twin
raw-byte parity, and gather-vs-mxu BIT-IDENTITY (dist/parent, direction
schedule, exchange bytes) across packed, unpacked-fallback, sparse-hybrid,
multisource, x8 sharded and superstep-checkpoint kill/resume paths.

Fixture shapes mirror the direction suite: a STAR (hub explosion), a PATH
deeper than the packed 62-level cap (fallback-under-mxu), a G(n,m) whose
ramp makes the Beamer predicate actually switch (mixed sparse-push /
mxu-pull levels), and an R-MAT (skewed degrees -> multiple degree classes,
scrambled relabel keys)."""

import os

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph import adj_tiles as AT
from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import RelayEngine
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.ops import relay_mxu as MX

needs_native = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)

SOURCE = 3


def star_graph(n: int = 256) -> Graph:
    hub = np.zeros(n - 1, np.int32)
    leaves = np.arange(1, n, dtype=np.int32)
    return Graph(n, np.concatenate([hub, leaves]),
                 np.concatenate([leaves, hub]))


@pytest.fixture(scope="module")
def gnm():
    return gnm_graph(1 << 10, 3 << 10, seed=5)


@pytest.fixture(scope="module")
def rmat():
    return rmat_graph(8, 8, seed=7)


def assert_oracle(g, res, s):
    d, _ = queue_bfs(g, s)
    _, p = canonical_bfs(g, s)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert check(g, res.dist, res.parent, s) == []


def assert_same(a, b):
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)
    assert a.num_levels == b.num_levels


# ---------------------------------------------------------------------------
# Knob surface.
# ---------------------------------------------------------------------------

def test_resolve_expansion_knobs(monkeypatch):
    monkeypatch.setenv("BFS_TPU_EXPANSION", "mxu")
    assert MX.resolve_expansion() == "mxu"
    assert MX.resolve_expansion("gather") == "gather"  # arg wins
    monkeypatch.setenv("BFS_TPU_EXPANSION", "tensor")
    with pytest.raises(ValueError):
        MX.resolve_expansion()
    monkeypatch.setenv("BFS_TPU_MXU_KERNEL", "mosaic")
    with pytest.raises(ValueError):
        MX.resolve_mxu_kernel()
    monkeypatch.setenv("BFS_TPU_MXU_KERNEL", "xla")
    assert MX.resolve_mxu_kernel() == "xla"


def test_tiles_budget_gate(monkeypatch):
    monkeypatch.setenv("BFS_TPU_MXU_TILE_GB", "0.000001")  # ~1 KB
    rng = np.random.default_rng(0)
    src = rng.integers(0, 4096, 4000)
    dst = rng.integers(0, 4096, 4000)
    keys = AT.keys_from_new2old(np.arange(4096), 4096)
    with pytest.raises(ValueError):
        AT.build_adj_tiles_host(
            src, dst, rows=4096, cols=4096, keys2d=keys,
            budget_bytes=MX.tiles_budget_bytes(),
        )


# ---------------------------------------------------------------------------
# Tile layout: host oracle vs device arm, schema, occupancy.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rows,cols,e", [(200, 200, 900), (4000, 300, 2500),
                                         (64, 9000, 50), (64, 64, 0)])
def test_tile_builders_bit_identical(rows, cols, e):
    rng = np.random.default_rng(rows + cols + e)
    src = rng.integers(0, rows, e)
    dst = rng.integers(0, cols, e)
    if e:  # duplicate edges must OR identically on both arms
        src = np.concatenate([src, src[:7]])
        dst = np.concatenate([dst, dst[:7]])
    keys = AT.keys_from_new2old(rng.permutation(rows).astype(np.int64), rows)
    h = AT.build_adj_tiles_host(src, dst, rows=rows, cols=cols, keys2d=keys)
    d = AT.build_adj_tiles_device(src, dst, rows=rows, cols=cols, keys2d=keys)
    for f in ("tiles", "row_idx", "col_id", "sb_indptr", "keys2d"):
        assert getattr(h, f).tobytes() == getattr(d, f).tobytes(), f
    assert (h.rows, h.cols, h.rtp, h.vtp, h.nt) == (
        d.rows, d.cols, d.rtp, d.vtp, d.nt
    )


def test_tiles_schema_round_trip_and_occupancy(rmat):
    eng = RelayEngine(rmat, expansion="mxu")
    at = eng.adj_tiles
    rt = AT.tiles_from_arrays(AT.tiles_to_arrays(at))
    assert rt.tiles.tobytes() == at.tiles.tobytes()
    assert (rt.nt, rt.vtp, rt.rtp, rt.rows, rt.cols) == (
        at.nt, at.vtp, at.rtp, at.rows, at.cols
    )
    hist = AT.tile_occupancy_hist(at)
    assert hist["tiles"] == at.nt
    assert sum(hist["buckets"].values()) == at.nt
    # every UNIQUE edge of the relay CSR landed as one tile bit
    # (duplicate edges OR onto the same bit by design)
    rg = eng.relay_graph
    deg = np.diff(np.asarray(rg.adj_indptr[: rg.vr + 1], dtype=np.int64))
    srcs = np.repeat(np.arange(rg.vr, dtype=np.int64), deg)
    uniq = np.unique(srcs * rg.vr + np.asarray(rg.adj_dst, np.int64)).size
    assert hist["edge_bits"] == uniq
    # a foreign schema version must refuse to load
    arrs = AT.tiles_to_arrays(at)
    arrs["dims"] = arrs["dims"].copy()
    arrs["dims"][0] = 999
    with pytest.raises(ValueError):
        AT.tiles_from_arrays(arrs)


def test_tiles_sidecar_bundle_round_trip(rmat, tmp_path):
    from bfs_tpu.cache.layout import LayoutCache, load_or_build_tiles

    rg = RelayEngine(rmat).relay_graph
    cache = LayoutCache(str(tmp_path))
    at1, info1 = load_or_build_tiles(rg, cache=cache)
    assert info1["cache"] == "miss"
    at2, info2 = load_or_build_tiles(rg, cache=cache)
    assert info2["cache"] == "hit"
    assert at1.tiles.tobytes() == at2.tiles.tobytes()
    assert at1.nt == at2.nt


# ---------------------------------------------------------------------------
# Kernel vs XLA twin: raw-byte parity (the PAL005 oracle's contract, also
# pinned here at shapes the lint-scale spec does not cover).
# ---------------------------------------------------------------------------

@pytest.mark.mxu_smoke
@pytest.mark.parametrize("rows,cols,e,fr", [
    (200, 200, 900, 0.4), (4000, 300, 2500, 0.02), (500, 9000, 3000, 0.9),
])
def test_kernel_twin_bit_identical(rows, cols, e, fr):
    import jax.numpy as jnp

    rng = np.random.default_rng(e)
    src = rng.integers(0, rows, e)
    dst = rng.integers(0, cols, e)
    n2o = rng.permutation(rows).astype(np.int64)
    keys = AT.keys_from_new2old(n2o, rows)
    at = AT.build_adj_tiles_host(src, dst, rows=rows, cols=cols, keys2d=keys)
    ops = MX.mxu_device_operands(at)
    nw = AT.round_up(rows, 32) // 32
    fbits = rng.random(rows) < fr
    fw = np.zeros(nw, np.uint32)
    for u in np.flatnonzero(fbits):
        fw[u >> 5] |= np.uint32(1) << np.uint32(u & 31)
    fw = jnp.asarray(fw)
    kw = dict(rows=rows, cols=cols, rtp=at.rtp, vtp=at.vtp)
    twin = np.asarray(MX.expand_frontier_mxu_xla(fw, ops, **kw))
    kern = np.asarray(
        MX.expand_frontier_mxu(fw, ops, interpret=True, **kw)
    )
    assert twin.tobytes() == kern.tobytes()
    # and both equal the brute-force min-original-id candidate
    ref = np.full(cols, 0xFFFFFFFF, np.uint64)
    for u, v in zip(src, dst):
        if fbits[u]:
            ref[v] = min(ref[v], int(n2o[u]))
    np.testing.assert_array_equal(twin.astype(np.uint64), ref)


# ---------------------------------------------------------------------------
# Engine: forced mxu vs gather — oracle-exact AND bit-identical.
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.mxu_smoke
def test_mxu_vs_gather_bit_identical_rmat(rmat):
    eg = RelayEngine(rmat)
    em = RelayEngine(rmat, expansion="mxu")
    assert em.expansion == "mxu" and em.adj_tiles is not None
    for s in (0, SOURCE, 17):
        assert_same(eg.run(s), em.run(s))
    assert_oracle(rmat, em.run(SOURCE), SOURCE)


@needs_native
@pytest.mark.parametrize("builder", ["host", "device"])
def test_mxu_builders_same_results(rmat, builder, monkeypatch):
    monkeypatch.setenv("BFS_TPU_TILES_BUILD", builder)
    em = RelayEngine(rmat, expansion="mxu")
    assert_oracle(rmat, em.run(SOURCE), SOURCE)


@needs_native
@pytest.mark.parametrize("fixture", ["star", "gnm"])
@pytest.mark.parametrize("direction", ["pull", "auto"])
def test_mxu_vs_gather_direction_matrix(fixture, direction, gnm):
    g = star_graph() if fixture == "star" else gnm
    eg = RelayEngine(g, direction=direction)
    em = RelayEngine(g, direction=direction, expansion="mxu")
    assert_same(eg.run(SOURCE), em.run(SOURCE))
    # schedule + occupancy bit-identity: the predicate sees the SAME
    # masses on both arms, so the per-level record cannot differ.
    cg = eg.run_level_curve(SOURCE)
    cm = em.run_level_curve(SOURCE)
    assert cg["direction_schedule"]["schedule"] == \
        cm["direction_schedule"]["schedule"]
    assert cg["occupancy"] == cm["occupancy"]


@needs_native
def test_mxu_auto_actually_switches(gnm):
    """The mixed-arm case: auto must run BOTH the sparse push body (key
    payloads) and the mxu pull body in one traversal, and still land
    oracle-exact."""
    deg = np.bincount(np.asarray(gnm.src), minlength=gnm.num_vertices)
    s = int(np.argmax(deg))
    em = RelayEngine(gnm, direction="auto", expansion="mxu")
    curve = em.run_level_curve(s)
    sched = curve["direction_schedule"]["schedule"]
    assert "push" in sched and "pull" in sched, sched
    assert_oracle(gnm, em.run(s), s)


@needs_native
def test_mxu_sparse_hybrid_off(gnm):
    eg = RelayEngine(gnm, sparse_hybrid=False)
    em = RelayEngine(gnm, sparse_hybrid=False, expansion="mxu")
    assert_same(eg.run(SOURCE), em.run(SOURCE))


@needs_native
def test_mxu_unpacked_carry(gnm, monkeypatch):
    monkeypatch.setenv("BFS_TPU_PACKED", "0")
    eg = RelayEngine(gnm)
    em = RelayEngine(gnm, expansion="mxu")
    assert not em.packed
    assert_same(eg.run(SOURCE), em.run(SOURCE))


@needs_native
def test_mxu_deep_path_unpacked_fallback():
    """>62 levels: the packed cap exit must re-run unpacked THROUGH the
    mxu arm (the unpacked mxu superstep + key-valued int32 parents)."""
    g = path_graph(70)
    eg = RelayEngine(g)
    em = RelayEngine(g, expansion="mxu")
    a, b = eg.run(0), em.run(0)
    assert a.num_levels == 70
    assert_same(a, b)
    assert_oracle(g, b, 0)


@needs_native
def test_mxu_multisource_parity(rmat):
    eg = RelayEngine(rmat)
    em = RelayEngine(rmat, expansion="mxu")
    sources = [0, 3, 9, 17]
    mg = eg.run_multi(sources)
    mm = em.run_multi(sources)
    np.testing.assert_array_equal(mg.dist, mm.dist)
    np.testing.assert_array_equal(mg.parent, mm.parent)
    assert mg.num_levels == mm.num_levels


@needs_native
def test_mxu_stepped_runner_parity(gnm):
    """The observability surface: SuperstepRunner's stepped relay path
    must decode mxu key parents (run_parallel --engine relay found the
    slot-mapping bug — to_original gathered keys through src_l1)."""
    from bfs_tpu.models.bfs import SuperstepRunner

    em = RelayEngine(gnm, expansion="mxu")
    runner = SuperstepRunner.__new__(SuperstepRunner)
    # build the runner over the SAME engine (the public ctor builds its
    # own; the contract under test is to_original's decode)
    runner.engine = "relay"
    runner._relay = em
    runner.num_vertices = em.relay_graph.num_vertices
    runner._old2new = em.relay_graph.old2new
    runner._step = em.step
    res = runner.run(SOURCE)
    assert_same(RelayEngine(gnm).run(SOURCE), res)
    assert_oracle(gnm, res, SOURCE)


@needs_native
def test_mxu_device_checker_path(rmat):
    """to_original_device must decode key parents (NOT slot-map them):
    the sampled-integrity serve path and bench's device verification both
    route through it."""
    import jax
    import jax.numpy as jnp

    em = RelayEngine(rmat, expansion="mxu")
    rg = em.relay_graph
    st = em._fused(
        jnp.int32(int(rg.old2new[SOURCE])), rg.num_vertices
    )
    dd, pp = jax.device_get(em.to_original_device(st, SOURCE))
    res = em.run(SOURCE)
    np.testing.assert_array_equal(dd, res.dist)
    np.testing.assert_array_equal(pp, res.parent)


@needs_native
def test_mxu_forced_packed_parent_overflow_raises(monkeypatch):
    """BFS_TPU_PACKED=1 + mxu needs V <= 2^26 (original ids in the parent
    field): the guard must raise, not silently truncate ids."""
    em = RelayEngine(rmat_graph(6, 4, seed=1), expansion="mxu")
    # fits comfortably here — the guard path is exercised via the
    # resolver directly to avoid building a 2^26-vertex fixture
    from bfs_tpu.ops.packed import packed_parent_fits

    assert packed_parent_fits(em.relay_graph.num_vertices)
    assert not packed_parent_fits((1 << 26) + 1)


# ---------------------------------------------------------------------------
# Sharded x8 (the tier-1 virtual mesh).
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.mxu_smoke
def test_sharded_x8_mxu_bit_identical(gnm):
    from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

    mesh = make_mesh(graph=8, batch=1)
    rg_, cg = bfs_sharded(
        gnm, SOURCE, mesh=mesh, engine="relay", direction="auto",
        telemetry=True,
    )
    rm, cm = bfs_sharded(
        gnm, SOURCE, mesh=mesh, engine="relay", direction="auto",
        telemetry=True, expansion="mxu",
    )
    assert_same(rg_, rm)
    # the ISSUE 15 acceptance triple: dist/parent, direction schedule,
    # exchange bytes — all bit-identical between the arms.
    assert cg["direction_schedule"]["schedule"] == \
        cm["direction_schedule"]["schedule"]
    assert cg["exchange"]["bytes_per_level"] == \
        cm["exchange"]["bytes_per_level"]
    assert cg["exchange"]["schedule"] == cm["exchange"]["schedule"]
    # and single-chip parity closes the loop
    assert_same(RelayEngine(gnm, direction="auto").run(SOURCE), rm)


@needs_native
def test_sharded_x2_mxu_pull(rmat):
    from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

    mesh = make_mesh(graph=2, batch=1)
    a = bfs_sharded(rmat, SOURCE, mesh=mesh, engine="relay",
                    direction="pull")
    b = bfs_sharded(rmat, SOURCE, mesh=mesh, engine="relay",
                    direction="pull", expansion="mxu")
    assert_same(a, b)
    assert_oracle(rmat, b, SOURCE)


# ---------------------------------------------------------------------------
# Superstep-checkpoint kill/resume through the mxu arm (ISSUE 15 x 14).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mxu_eng(gnm):
    return RelayEngine(gnm, direction="auto", expansion="mxu")


@pytest.fixture(scope="module")
def mxu_golden(mxu_eng):
    return mxu_eng.run(SOURCE), mxu_eng.run_level_curve(SOURCE)


def _mgr(tmp_path, k=1):
    from bfs_tpu.resilience.superstep_ckpt import (
        CkptConfig,
        SuperstepCheckpointer,
    )

    return SuperstepCheckpointer(
        tmp_path, {"t": "mxu"}, cfg=CkptConfig("every", k)
    )


@needs_native
def test_mxu_segmented_parity(mxu_eng, mxu_golden, tmp_path):
    res, curve = mxu_eng.run_segmented(
        SOURCE, ckpt=_mgr(tmp_path, k=2), telemetry=True
    )
    gres, gcurve = mxu_golden
    assert_same(res, gres)
    assert curve["direction_schedule"]["schedule"] == \
        gcurve["direction_schedule"]["schedule"]
    assert curve["occupancy"] == gcurve["occupancy"]


@needs_native
@pytest.mark.chaos
def test_mxu_kill_resume_bit_identical(mxu_eng, mxu_golden, tmp_path):
    """Kill a mid-traversal segment on the mxu arm, resume, assert
    bit-identity incl. the schedule — the hysteresis pair and the mxu
    carry both ride the epoch."""
    from bfs_tpu.resilience import faults
    from bfs_tpu.resilience.faults import FaultInjected

    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            mxu_eng.run_segmented(
                SOURCE, ckpt=_mgr(tmp_path), telemetry=True
            )
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = _mgr(tmp_path)
    res, curve = mxu_eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    assert mgr.report()["resumed_from_epoch"] == 2
    gres, gcurve = mxu_golden
    assert_same(res, gres)
    assert curve["direction_schedule"]["schedule"] == \
        gcurve["direction_schedule"]["schedule"]


@needs_native
def test_sharded_segmented_mxu_parity(rmat, tmp_path):
    from bfs_tpu.parallel.sharded import (
        bfs_sharded,
        bfs_sharded_segmented,
        make_mesh,
    )
    from bfs_tpu.resilience.superstep_ckpt import (
        CkptConfig,
        SuperstepCheckpointer,
    )

    mesh = make_mesh(graph=2, batch=1)
    fused = bfs_sharded(
        rmat, SOURCE, mesh=mesh, engine="relay", expansion="mxu"
    )
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": "mxu-sharded"}, cfg=CkptConfig("every", 2),
        shards=2,
    )
    seg = bfs_sharded_segmented(
        rmat, SOURCE, mesh=mesh, ckpt=mgr, expansion="mxu"
    )
    assert_same(fused, seg)


# ---------------------------------------------------------------------------
# Probe memo (ISSUE 15 satellite) + probe/ledger expansion arms.
# ---------------------------------------------------------------------------

def test_probe_verdict_memo_round_trip(rmat, tmp_path, monkeypatch):
    from bfs_tpu.cache import layout as CL

    monkeypatch.setenv("BFS_TPU_CACHE_DIR", str(tmp_path))
    eng = RelayEngine(rmat)
    key = CL.probe_verdict_key(eng)
    assert CL.load_probe_verdict(key) is None
    CL.save_probe_verdict(key, {"rowmin": {"selected": "xla"}})
    assert CL.load_probe_verdict(key) == {"rowmin": {"selected": "xla"}}
    # knob env changes the key (a re-probe, not a stale replay)
    monkeypatch.setenv("BFS_TPU_MXU_KERNEL", "xla")
    assert CL.probe_verdict_key(eng) != key
    # corruption drops the file and reports a miss
    path = os.path.join(str(tmp_path), "layout", "probe", f"{key}.json")
    with open(path, "w") as f:
        f.write("{broken")
    assert CL.load_probe_verdict(key) is None
    assert not os.path.exists(path)


def test_engine_probe_memoized_across_inits(rmat, tmp_path, monkeypatch):
    """The satellite's point: a second engine init over the same layout
    must NOT re-pay the K-loop probe — the verdict replays from the memo
    next to the layout bundle."""
    import bfs_tpu.models.bfs as MB

    monkeypatch.setenv("BFS_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("BFS_TPU_PHASE_PROBE", "force")
    calls = []

    def fake_probe(eng, **kw):
        calls.append(1)
        return {
            "rowmin": {"selected": "xla", "selection_basis": "measured"},
            "state_update": {
                "selected": "xla", "selection_basis": "measured",
            },
        }

    monkeypatch.setattr(
        "bfs_tpu.profiling.probe_phase_kernels", fake_probe
    )
    e1 = RelayEngine(rmat)
    e2 = RelayEngine(rmat)
    assert len(calls) == 1, "warm engine init re-paid the phase probe"
    assert e1.phase_probe.get("memo") == "miss"
    assert e2.phase_probe.get("memo") == "hit"
    assert e2.phase_selection["rowmin"] == "xla"


@needs_native
def test_probe_and_ledger_carry_expansion_arms(mxu_eng):
    from bfs_tpu.profiling import probe_phase_kernels, superstep_phase_ledger

    probe = probe_phase_kernels(mxu_eng, loops=1, repeats=1)
    rec = probe["expansion"]
    assert set(rec["arms"]) >= {"gather", "mxu"}
    assert rec["selected"] in ("gather", "mxu")
    assert "measured" in rec["selection_basis"]
    led = superstep_phase_ledger(mxu_eng, loops=1, repeats=1)
    exp = led["phases"]["expansion"]
    # the ledger reports the arm the ENGINE runs, with both arms' seconds
    assert exp["selected"] == "mxu"
    assert "gather" in exp["arms"] and "mxu" in exp["arms"]
    assert exp["seconds"] == exp["arms"]["mxu"]
    assert exp["tiles"] == mxu_eng.adj_tiles.nt


def test_expansion_detail_per_level_join():
    from bfs_tpu.bench import _expansion_per_level

    detail = {
        "expansion": {"arm": "mxu"},
        "direction_schedule": {
            "schedule": ["push", "pull", "pull", "push"]
        },
    }
    _expansion_per_level(detail)
    assert detail["expansion"]["per_level"] == [
        "sparse", "mxu", "mxu", "sparse"
    ]


@needs_native
def test_auto_resolves_gather_off_tpu(rmat):
    """In-container the measured half never runs: auto must resolve to
    gather with the basis on record (never a silent default)."""
    eng = RelayEngine(rmat, expansion="auto")
    assert eng.expansion == "gather"
    assert "non-tpu" in (eng.expansion_basis or "") or "gather" in (
        eng.expansion_basis or ""
    )
    assert eng.adj_tiles is None  # no tiles built for an unprobed arm
