"""Test harness config: force an 8-device virtual CPU platform BEFORE jax
imports — the single-host multi-device methodology mirroring the reference
benchmark's "master + N workers on one machine" setup
(docs/BigData_Project.pdf §1.5, SURVEY.md §4)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Tier-1 compile-budget pin: default the direction knob to the dense
# (pull) program so the many tests that run engines with DEFAULT knobs
# compile the cheapest superstep body on this 2-core container.  Every
# direction/exchange behavior has dedicated coverage that passes
# `direction=`/`exchange=` explicitly (test_direction.py,
# test_direction_sharded.py, test_exchange.py) — explicit arguments win
# over this env default, and a caller-exported BFS_TPU_DIRECTION is
# respected (setdefault).
os.environ.setdefault("BFS_TPU_DIRECTION", "pull")
os.environ.setdefault("BFS_TPU_EXCHANGE", "bitmap")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu",
# overriding the env var; pin CPU back explicitly for the test suite.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from bfs_tpu.graph.csr import Graph

# tinyCG.txt contents (reference test-sets/tinyCG.txt; the paper's worked
# example, docs/BigData_Project.pdf §1.2 Table 1): 6 vertices, 8 edges.
TINY_V = 6
TINY_EDGES = [(0, 5), (2, 4), (2, 3), (1, 2), (0, 1), (3, 4), (3, 5), (0, 2)]
TINY_TEXT = "6\n8\n" + "\n".join(f"{u} {v}" for u, v in TINY_EDGES) + "\n"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_TEST_SETS = "/root/reference/test-sets"


@pytest.fixture
def tiny_graph() -> Graph:
    return Graph.from_undirected_edges(TINY_V, np.array(TINY_EDGES))


@pytest.fixture
def medium_graph() -> Graph:
    """A mediumG-shape graph (250 V / 1,273 E — the reference benchmark's
    middle size): the in-repo fixture test-sets/randomG.txt, so no test
    depends on the read-only reference mount.  When the reference's actual
    mediumG.txt is present it is used instead, for closer parity."""
    from bfs_tpu.graph.io import read_sedgewick

    ref = os.path.join(REFERENCE_TEST_SETS, "mediumG.txt")
    if os.path.exists(ref):
        return read_sedgewick(ref)
    return read_sedgewick(os.path.join(REPO_ROOT, "test-sets", "randomG.txt"))
