"""Tests for bfs_tpu.obs: spans (nesting, Chrome-trace export, journal
round-trip through a resumed run, SIGTERM-style flush), device superstep
telemetry (exit-only pulls asserted with a jax.device_get spy, level
curves vs the oracle across engines), the MetricsRegistry snapshot with
both exporter formats, the eviction counter satellite, and the
percentile / zero-query ServeMetrics edge cases."""

from __future__ import annotations

import json

import numpy as np
import pytest

from bfs_tpu.graph.generators import path_graph, rmat_graph
from bfs_tpu.obs import registry as obs_registry
from bfs_tpu.obs import spans as obs_spans
from bfs_tpu.obs.spans import (
    chrome_trace,
    flush_open_spans,
    instant,
    journal_spans,
    snapshot_events,
    span,
    span_report,
    stitch_journal_trace,
)
from bfs_tpu.obs.telemetry import TEL_SLOTS, level_curve, render_curve_ascii
from bfs_tpu.oracle.bfs import queue_bfs
from bfs_tpu.utils.metrics import ServeMetrics, percentile

INF = np.iinfo(np.int32).max


@pytest.fixture(autouse=True)
def _clean_spans():
    obs_spans.drain_events()
    yield
    obs_spans.drain_events()


@pytest.fixture(scope="module")
def small_graph():
    return rmat_graph(9, 8, seed=7)


def oracle_curve(graph, source):
    dist, _ = queue_bfs(graph, source)
    reached = dist != INF
    return int(reached.sum()), [int(x) for x in np.bincount(dist[reached])]


# ---------------------------------------------------------------------------
# Spans.
# ---------------------------------------------------------------------------

def test_span_nesting_containment():
    with span("outer", phase="x"):
        with span("inner"):
            pass
    evs = [e for e in snapshot_events() if e["ph"] == "X"]
    assert [e["name"] for e in evs] == ["inner", "outer"]  # close order
    inner, outer = evs
    # Perfetto infers nesting from containment on one tid: outer must
    # envelop inner in both start and end.
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
    assert inner["tid"] == outer["tid"]
    assert outer["args"] == {"phase": "x"}


def test_span_decorator_and_report():
    @span("unit.work")
    def work(x):
        return x + 1

    assert work(1) == 2
    assert work(2) == 3
    rep = span_report()
    assert rep["unit.work"]["count"] == 2
    assert rep["unit.work"]["total_s"] > 0


def test_span_disabled_by_env(monkeypatch):
    monkeypatch.setenv("BFS_TPU_SPANS", "0")
    with span("invisible"):
        instant("also.invisible")
    assert snapshot_events() == []


def test_span_error_annotated():
    with pytest.raises(ValueError):
        with span("fails"):
            raise ValueError("boom")
    (ev,) = snapshot_events()
    assert ev["args"]["error"] == "ValueError"


def test_flush_open_spans_sigterm_shape():
    """The SIGTERM path: a still-open span gets its real duration so far
    plus the flush marker — an interrupted run leaves a usable trace."""
    sp = span("bench.repeat", i=1)
    sp.__enter__()
    n = flush_open_spans("signal:SIGTERM")
    assert n == 1
    (ev,) = snapshot_events()
    assert ev["name"] == "bench.repeat"
    assert ev["args"]["flushed"] == "signal:SIGTERM"
    assert ev["dur"] >= 1
    # Exiting after the flush must not double-emit.
    sp.__exit__(None, None, None)
    assert len(snapshot_events()) == 1


def test_chrome_trace_is_perfetto_loadable_shape(tmp_path):
    with span("a"):
        instant("marker", graph="g")
    doc = chrome_trace()
    # Perfetto's JSON importer wants traceEvents with name/ph/ts/pid/tid;
    # complete events carry dur.  Round-trip through real JSON.
    doc = json.loads(json.dumps(doc))
    assert isinstance(doc["traceEvents"], list) and len(doc["traceEvents"]) == 2
    for ev in doc["traceEvents"]:
        for k in ("name", "ph", "ts", "pid", "tid"):
            assert k in ev, ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 1
    out = obs_spans.export_chrome_trace(str(tmp_path / "t.json"))
    assert json.load(open(out))["traceEvents"]


def test_journal_roundtrip_through_resumed_run(tmp_path):
    """A killed-and-resumed bench journals one spans:<k> record per
    process generation; the stitched trace holds every generation's
    events in order."""
    from bfs_tpu.resilience.journal import RunJournal

    cfg = {"bench": "t", "scale": 4}
    path = str(tmp_path / "run.jsonl")

    jr = RunJournal(path, cfg)
    with span("gen0.phase"):
        pass
    assert journal_spans(jr) == "spans:0"
    jr.close()

    # "Resume": same config reopens the same journal file.
    jr2 = RunJournal(path, cfg)
    assert "spans:0" in jr2.resumed_phases
    with span("gen1.phase"):
        pass
    assert journal_spans(jr2) == "spans:1"
    jr2.close()

    doc = stitch_journal_trace(path)
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["gen0.phase", "gen1.phase"]
    # Journaling drained the buffer: nothing double-counts.
    assert snapshot_events() == []
    # Empty buffer -> no-op, no empty record.
    jr3 = RunJournal(path, cfg)
    assert journal_spans(jr3) is None
    jr3.close()


def test_event_buffer_bounded(monkeypatch):
    monkeypatch.setattr(obs_spans, "MAX_EVENTS", 3)
    for i in range(5):
        instant(f"m{i}")
    assert len(snapshot_events()) == 3
    assert chrome_trace()["otherData"]["dropped_events"] == 2


# ---------------------------------------------------------------------------
# Device telemetry: exit-only pulls + oracle-checked level curves.
# ---------------------------------------------------------------------------

def test_relay_level_curve_one_device_get(monkeypatch, small_graph):
    """THE tentpole contract: collecting the curve costs exactly ONE
    jax.device_get, of the ~KB accumulators — never the V-sized state."""
    import jax

    from bfs_tpu.models.bfs import RelayEngine

    eng = RelayEngine(small_graph, sparse_hybrid=False)
    reached, hist = oracle_curve(small_graph, 0)

    calls = []
    real = jax.device_get

    def spy(x):
        calls.append(
            sum(int(np.asarray(getattr(l, "size", 1)))
                for l in jax.tree_util.tree_leaves(x))
        )
        return real(x)

    monkeypatch.setattr(jax, "device_get", spy)
    curve = eng.run_level_curve(0)
    monkeypatch.undo()

    assert len(calls) == 1, f"expected ONE pull at loop exit, saw {len(calls)}"
    # fv + fe + direction schedule + (changed, level): the push/pull
    # schedule (ISSUE 7) rides the SAME single loop-exit pull.
    assert calls[0] <= 3 * TEL_SLOTS + 2
    assert curve["reachable"] == reached
    assert curve["occupancy"] == hist


def test_relay_curve_sparse_hybrid_and_edges(small_graph):
    from bfs_tpu.models.bfs import RelayEngine

    eng = RelayEngine(small_graph, sparse_hybrid=True)
    reached, hist = oracle_curve(small_graph, 0)
    curve = eng.run_level_curve(0)
    assert curve["occupancy"] == hist
    # Frontier out-edges: level 0 is the source's own degree; every level
    # is non-negative and the array tracks occupancy's length.
    fe = curve["frontier_edges"]
    assert len(fe) == len(hist)
    assert all(e >= 0 for e in fe)
    assert curve["cap"] == 62 and 0 < curve["cap_proximity"] < 1


@pytest.mark.parametrize("engine", ["pull", "push"])
def test_bfs_level_curve_matches_oracle(engine, small_graph):
    from bfs_tpu.models.bfs import bfs_level_curve

    reached, hist = oracle_curve(small_graph, 0)
    curve = bfs_level_curve(small_graph, 0, engine=engine)
    assert curve["reachable"] == reached
    assert curve["occupancy"] == hist
    assert not curve["truncated"]


def test_level_curve_past_packed_cap_unpacked_fallback():
    """Deeper than the 62-level packed cap: the curve must come from the
    unpacked re-run (full depth), not a truncated packed loop."""
    from bfs_tpu.models.bfs import bfs_level_curve

    g = path_graph(100)
    curve = bfs_level_curve(g, 0, engine="pull")
    assert curve["levels"] == 100
    assert curve["reachable"] == 100
    assert curve["occupancy"] == [1] * 100


def test_multi_source_curve_sums_trees(small_graph):
    from bfs_tpu.models.multisource import bfs_multi_level_curve

    sources = [0, 5, 9]
    expected = sum(oracle_curve(small_graph, s)[0] for s in sources)
    curve = bfs_multi_level_curve(small_graph, sources, engine="pull")
    assert curve["reachable"] == expected
    assert curve["occupancy"][0] == len(sources)


def test_level_curve_host_math():
    fv = np.zeros(TEL_SLOTS, np.int32)
    fv[:4] = [1, 10, 100, 3]
    c = level_curve(fv, cap=62, reference_reached=114)
    assert c["occupancy"] == [1, 10, 100, 3]
    assert c["levels"] == 4 and c["peak_level"] == 2
    assert c["occupancy_sum_matches_reference"]
    assert "L  2" in render_curve_ascii(c)
    # Clamped deep levels mark the curve truncated but keep the sum exact.
    fv[TEL_SLOTS - 1] = 7
    c2 = level_curve(fv)
    assert c2["truncated"] and c2["reachable"] == 121
    # Wide (lo16/hi16) batched accumulator reconstructs exact int64 past
    # the int32 range: 3 + 2**17 * 65536 = 2**33 + 3.
    wide = np.zeros((TEL_SLOTS, 2), np.int32)
    wide[0] = [3, 1 << 17]
    c3 = level_curve(wide)
    assert c3["occupancy"] == [(1 << 33) + 3]


def test_multi_curve_wide_acc_consistency(small_graph):
    """The overflow-safe wide accumulator must agree exactly with the
    scalar path on an in-range workload."""
    from bfs_tpu.models.multisource import bfs_multi_level_curve

    c = bfs_multi_level_curve(small_graph, [0, 1], engine="push")
    a, _ = oracle_curve(small_graph, 0)
    b, _ = oracle_curve(small_graph, 1)
    assert c["reachable"] == a + b


# ---------------------------------------------------------------------------
# MetricsRegistry: one snapshot, two exporter formats.
# ---------------------------------------------------------------------------

def test_registry_snapshot_absorbs_all_surfaces():
    from bfs_tpu.analysis.runtime import bump_retrace
    from bfs_tpu.utils.metrics import bump_artifact

    reg = obs_registry.MetricsRegistry()
    reg.counter("graph_evictions", 3)
    sm = ServeMetrics()
    sm.bump("batches", 2)
    obs_registry.get_registry().register_serve(sm)  # global: via ctor too
    reg.register_serve(sm)
    reg.register_serve(sm)  # idempotent
    bump_artifact("layout_cache_hits")
    bump_retrace("test.obs_fn")
    with span("snap.unit"):
        pass

    snap = reg.snapshot(retrace_baseline={"test.obs_fn": 0})
    assert snap["counters"]["graph_evictions"] == 3
    assert snap["artifact_caches"]["layout_cache_hits"] >= 1
    assert snap["retraces"]["test.obs_fn"] >= 1
    assert snap["retrace_drift"]["test.obs_fn"] >= 1
    assert snap["spans"]["snap.unit"]["count"] == 1
    assert [r["counters"]["batches"] for r in snap["serve"]] == [2]
    json.loads(reg.to_json())  # exporter 1: valid JSON


def test_prometheus_exporter_format():
    reg = obs_registry.MetricsRegistry()
    reg.counter("graph_evictions")
    text = reg.to_prometheus()
    lines = [l for l in text.strip().splitlines() if l]
    assert any(l.startswith("# TYPE bfs_tpu_") for l in lines)
    for l in lines:
        if l.startswith("#"):
            continue
        name, value = l.split(" ", 1)
        assert name.startswith("bfs_tpu_")
        assert all(c.isalnum() or c == "_" for c in name)
        float(value)  # every sample parses as a number
    assert "bfs_tpu_counters_graph_evictions 1" in lines


def test_registry_drops_dead_serve_metrics():
    reg = obs_registry.MetricsRegistry()
    sm = ServeMetrics()
    reg.register_serve(sm)
    assert len(reg.snapshot()["serve"]) == 1
    del sm
    import gc

    gc.collect()
    assert reg.snapshot()["serve"] == []


def test_graph_registry_eviction_emits_counter_and_marker(small_graph):
    """Satellite: HBM-budget thrash is visible — an eviction lands both a
    registry counter and an instant trace marker."""
    from bfs_tpu.serve import GraphRegistry

    reg = obs_registry.get_registry()
    before = reg.count("graph_evictions")
    gr = GraphRegistry(device_budget_bytes=1)  # everything evicts everything
    gr.register("a", small_graph)
    gr.register("b", small_graph)
    gr.acquire("a", "pull")
    gr.acquire("b", "pull")  # evicts a
    assert gr.evictions >= 1
    assert reg.count("graph_evictions") > before
    marks = [e for e in snapshot_events()
             if e["ph"] == "i" and e["name"] == "registry.evict"]
    assert marks and marks[0]["args"]["graph"] == "a"
    assert marks[0]["args"]["bytes"] > 0


# ---------------------------------------------------------------------------
# utils.metrics edge cases (satellite).
# ---------------------------------------------------------------------------

def test_percentile_edge_cases():
    assert percentile([], 50) == 0.0
    assert percentile([], 0) == 0.0
    assert percentile([7.0], 0) == 7.0
    assert percentile([7.0], 100) == 7.0
    vals = [4.0, 1.0, 3.0, 2.0]
    assert percentile(vals, 0) == 1.0
    assert percentile(vals, 100) == 4.0
    assert percentile(vals, 50) == pytest.approx(2.5)
    assert percentile(range(101), 99) == pytest.approx(99.0)


def test_serve_metrics_report_zero_queries():
    rep = ServeMetrics().report()
    assert rep["queries"] == 0 and rep["served"] == 0
    assert rep["latency_p50_ms"] == 0.0 and rep["latency_p99_ms"] == 0.0
    assert rep["latency_mean_ms"] == 0.0
    assert rep["batch_size_mean"] == 0.0 and rep["batch_size_max"] == 0
    assert rep["queries_per_sec"] == 0.0
    assert rep["compile_hit_rate"] is None
    assert rep["result_cache_hit_rate"] is None
    assert rep["retries"]["device_retries"] == 0
    json.dumps(rep)  # JSON-ready even with no traffic


# ---------------------------------------------------------------------------
# CLI.
# ---------------------------------------------------------------------------

def test_obs_cli_trace_and_curve(tmp_path):
    from bfs_tpu.obs.__main__ import main as obs_main
    from bfs_tpu.resilience.journal import RunJournal

    path = str(tmp_path / "run.jsonl")
    jr = RunJournal(path, {"bench": "cli"})
    with span("bench.repeat"):
        pass
    journal_spans(jr)
    jr.put("level_curve", {"level_curve": {
        "occupancy": [1, 2], "levels": 2, "reachable": 3,
        "cap": 62, "cap_proximity": 2 / 62,
    }})
    jr.close()

    out = str(tmp_path / "trace.json")
    assert obs_main(["trace", path, "-o", out]) == 0
    doc = json.load(open(out))
    assert [e["name"] for e in doc["traceEvents"]] == ["bench.repeat"]
    assert obs_main(["curve", path]) == 0
    assert obs_main(["snapshot"]) == 0
    assert obs_main(["snapshot", "--prom"]) == 0
