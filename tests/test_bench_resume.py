"""Subprocess fault-injection tests: SIGKILL the bench at phase boundaries
(``BFS_TPU_FAULT``), re-invoke with the same config, and prove the resumed
run finishes the SAME verified headline from the journal instead of
starting over (ISSUE 3 acceptance: the round-5 failure mode — rc=124 forty
seconds before the final check line — must be un-losable).

Tier-1 keeps one single-kill case (kill at the verification boundary, the
exact place round 5 died); the every-phase sweep is ``slow``.  The bench
config is tiny (s8, push engine, CPU) so each subprocess run is seconds.
All runs share one artifact cache (graph npz built once); each test case
gets a fresh journal dir, because the journal — not the caches — is the
resume state under test.
"""

import json
import os
import signal
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_SCALE": "8",
    "BENCH_EDGE_FACTOR": "4",
    "BENCH_ROOTS": "3",
    "BENCH_REPEATS": "2",
    "BENCH_ENGINE": "push",
    "BENCH_TIME_BUDGET": "600",
}

#: Deterministic headline fields: identical across ANY two runs of this
#: config — timed fields (value, batch_times) are only identical between a
#: killed run and ITS resume, which is asserted separately.
DETERMINISTIC_DETAILS = (
    "roots",
    "directed_edges_traversed",
    "vertices_reached",
    "supersteps_last_root",
    "num_vertices",
    "num_directed_edges",
    "check",
    "engine",
)


def run_bench(cache_dir, journal_dir, fault=None, timeout=240, extra_env=None):
    env = {**os.environ, **BENCH_ENV, **(extra_env or {})}
    env["BFS_TPU_CACHE_DIR"] = str(cache_dir)
    env["BFS_TPU_JOURNAL_DIR"] = str(journal_dir)
    env.pop("BFS_TPU_FAULT", None)
    if fault is not None:
        env["BFS_TPU_FAULT"] = fault
    proc = subprocess.run(
        [sys.executable, "-m", "bfs_tpu.bench"],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO_ROOT,
    )
    lines = [
        json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")
    ]
    return proc, lines


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    return tmp_path_factory.mktemp("bench_cache")


@pytest.fixture(scope="module")
def golden(cache_dir, tmp_path_factory):
    """One uninterrupted run: the reference headline every resumed run's
    deterministic fields must reproduce."""
    proc, lines = run_bench(cache_dir, tmp_path_factory.mktemp("golden_journal"))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert lines, "no headline emitted"
    head = lines[-1]
    assert head["details"]["check"].startswith("passed (3/3")
    return head


def test_kill_at_verify_then_resume_finishes_same_headline(
    cache_dir, golden, tmp_path
):
    # Kill at the first verification boundary: timed repeats are already
    # journaled, one root's verdict is in, two are not.
    proc1, lines1 = run_bench(cache_dir, tmp_path, fault="kill:verify")
    assert proc1.returncode == -signal.SIGKILL
    assert "[fault] SIGKILL at phase boundary" in proc1.stderr
    provisional = lines1[-1]
    assert provisional["details"]["check"].startswith("pending")

    # Re-invoke with the same config: must resume, not restart.
    proc2, lines2 = run_bench(cache_dir, tmp_path)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    final = lines2[-1]

    # The resume finishes the KILLED run's headline: the TEPS value and the
    # timed repeats are bit-identical to what the dead process had already
    # measured and journaled — nothing was re-timed, nothing was lost.
    assert final["value"] == provisional["value"]
    assert (
        final["details"]["batch_times"] == provisional["details"]["batch_times"]
    )
    assert final["details"]["check"].startswith("passed (3/3")

    # Resume skipped the completed phases (no reference re-run, journaled
    # repeat times, the already-verified root not re-verified).
    assert "journal: reference run restored" in proc2.stderr
    assert "journal: 2/2 timed repeats restored" in proc2.stderr
    assert "reference run (compile + warm)" not in proc2.stderr
    assert "verified (journal)" in proc2.stderr

    # And the headline matches an independent uninterrupted run on every
    # deterministic field.
    assert final["metric"] == golden["metric"]
    assert final["unit"] == golden["unit"]
    for k in DETERMINISTIC_DETAILS:
        assert final["details"][k] == golden["details"][k], k

    # A third invocation is a pure replay of the identical headline.
    proc3, lines3 = run_bench(cache_dir, tmp_path)
    assert proc3.returncode == 0
    assert "replaying final headline" in proc3.stderr
    assert lines3[-1] == final


@pytest.mark.slow
@pytest.mark.parametrize(
    "phase",
    ["graph", "reference", "roots", "warm", "repeat:2", "provisional",
     "verify:3", "headline"],
)
def test_kill_sweep_every_phase_boundary(cache_dir, golden, tmp_path, phase):
    proc1, lines1 = run_bench(cache_dir, tmp_path, fault=f"kill:{phase}")
    assert proc1.returncode == -signal.SIGKILL, (
        f"fault kill:{phase} did not fire: rc={proc1.returncode}\n"
        + proc1.stderr[-2000:]
    )

    proc2, lines2 = run_bench(cache_dir, tmp_path)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    final = lines2[-1]
    assert final["metric"] == golden["metric"]
    for k in DETERMINISTIC_DETAILS:
        assert final["details"][k] == golden["details"][k], k

    # Kills at-or-after the timing phase additionally pin the value to the
    # killed run's own (already-emitted) provisional measurement.
    killed_provisionals = [
        l for l in lines1 if l["details"].get("provisional")
    ]
    if killed_provisionals:
        assert final["value"] == killed_provisionals[-1]["value"]

    # Idempotent completion: one more invocation replays, bit-identical.
    proc3, lines3 = run_bench(cache_dir, tmp_path)
    assert lines3[-1] == final


def test_direction_forced_resume_replays_schedule(
    cache_dir, tmp_path, tmp_path_factory
):
    """ISSUE 7 satellite: a direction-forced relay run killed AFTER the
    level-curve boundary resumes with the journaled schedule restored —
    and the schedule matches an independent golden run bit-identically
    (it is a pure on-device function of graph + thresholds, and the
    direction knobs are part of the journal config key)."""
    from bfs_tpu.graph import benes

    if not benes.native_available():
        pytest.skip("native benes router unavailable")
    env = {
        "BENCH_ENGINE": "relay",
        "BENCH_SPARSE": "1",
        "BFS_TPU_DIRECTION": "auto",
        "BENCH_ROOTS": "2",
        "BENCH_CHECK_ROOTS": "2",
    }
    gp, glines = run_bench(
        cache_dir, tmp_path_factory.mktemp("dir_golden_j"), extra_env=env
    )
    assert gp.returncode == 0, gp.stderr[-2000:]
    gsched = glines[-1]["details"].get("direction_schedule")
    assert gsched is not None, "relay headline shipped no direction_schedule"
    assert set(gsched["schedule"]) <= {"push", "pull"}
    assert gsched["mode"] == "auto"

    # Kill at the verification boundary — the curve + schedule are
    # already journaled; the resume must RESTORE them, not re-run.
    p1, _ = run_bench(cache_dir, tmp_path, fault="kill:verify", extra_env=env)
    assert p1.returncode == -signal.SIGKILL
    p2, lines2 = run_bench(cache_dir, tmp_path, extra_env=env)
    assert p2.returncode == 0, p2.stderr[-2000:]
    assert "journal: level curve restored" in p2.stderr
    final = lines2[-1]
    assert final["details"]["direction_schedule"]["schedule"] == (
        gsched["schedule"]
    )

    # A different threshold knob maps to a DIFFERENT journal (config
    # key): the run starts fresh instead of resuming the auto journal.
    p3, lines3 = run_bench(
        cache_dir, tmp_path_factory.mktemp("dir_pull_j"),
        extra_env={**env, "BFS_TPU_DIRECTION": "pull"},
    )
    assert p3.returncode == 0, p3.stderr[-2000:]
    sched3 = lines3[-1]["details"]["direction_schedule"]
    assert sched3["mode"] == "pull"
    assert set(sched3["schedule"]) == {"pull"}


@pytest.mark.slow
def test_multichip_bench_journals_and_rotates_prejournal_capture(
    cache_dir, tmp_path
):
    """ISSUE 11: the MULTICHIP bench journals its phases like the
    single-chip run, and its resume path ROTATES a pre-journal-schema
    file at the journal path (the round-1..5 ``MULTICHIP_r0*.json``
    capture shape) instead of truncating it — evidence is never
    destroyed, and the fresh run completes the same headline."""
    from bfs_tpu.graph import benes

    if not benes.native_available():
        pytest.skip("native benes router unavailable")
    env = {
        "BENCH_ENGINE": "relay",
        "BENCH_MESH": "2",
        "BENCH_ROOTS": "2",
        "BENCH_REPEATS": "1",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    p1, lines1 = run_bench(cache_dir, tmp_path, extra_env=env, timeout=420)
    assert p1.returncode == 0, p1.stderr[-2000:]
    head = lines1[-1]
    assert head["metric"].startswith("rmat8_multichip2")
    ex = head["details"]["exchange"]
    assert ex["total_bytes"] == sum(ex["bytes_per_level"])
    assert head["details"]["sharded_phases"]["shards"] == 2
    assert head["details"]["check"].startswith("passed (2/2")

    # A second invocation is a pure replay.
    p2, lines2 = run_bench(cache_dir, tmp_path, extra_env=env, timeout=420)
    assert p2.returncode == 0, p2.stderr[-2000:]
    assert "replaying headline" in p2.stderr
    assert lines2[-1] == head

    # Overwrite the journal with a pre-journal multichip capture (the
    # old driver schema: JSON, but no record sequence).  The next run
    # must rotate it aside — NOT truncate it, NOT crash — and re-run.
    journals = [
        f for f in os.listdir(tmp_path) if f.endswith(".jsonl")
    ]
    assert len(journals) == 1
    jpath = os.path.join(str(tmp_path), journals[0])
    legacy = (
        '{"n_devices": 8, "rc": 0, "ok": true, "skipped": false,\n'
        ' "tail": "relay legs verified\\n"}\n'
    )
    with open(jpath, "w") as f:
        f.write(legacy)
    p3, lines3 = run_bench(cache_dir, tmp_path, extra_env=env, timeout=420)
    assert p3.returncode == 0, p3.stderr[-2000:]
    assert lines3, "post-rotation run emitted no headline"
    stale = jpath + ".stale.0"
    assert os.path.exists(stale), "pre-journal capture was not rotated"
    assert open(stale).read() == legacy, "rotated evidence was mutated"
    for k in ("roots", "vertices_reached", "num_vertices"):
        assert lines3[-1]["details"][k] == head["details"][k], k


@pytest.mark.slow
def test_raise_mode_fault_then_resume(cache_dir, golden, tmp_path):
    # raise: mode dies with a traceback (exception path, not SIGKILL) —
    # the journal must still carry every phase completed before the fault.
    proc1, _ = run_bench(cache_dir, tmp_path, fault="raise:roots")
    assert proc1.returncode not in (0, -signal.SIGKILL)
    assert "FaultInjected" in proc1.stderr

    proc2, lines2 = run_bench(cache_dir, tmp_path)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert "journal: reference run restored" in proc2.stderr
    final = lines2[-1]
    for k in DETERMINISTIC_DETAILS:
        assert final["details"][k] == golden["details"][k], k
