"""Tests for bfs_tpu.analysis.knobs — the knob-provenance pass (ISSUE 19):
every KNB rule must trip on a fixture and stay quiet on its near-miss,
the set-equality pins must fire in BOTH directions (a raw read is as
fatal as a dead registry row; a missing key member as fatal as an extra
one), the repo's own registry + sources + key builders + README must run
clean modulo the baseline, the registry defaults must equal the module
constants they replaced (the migration's no-behavior-change proof), the
content-addressed result cache must hit on an unchanged tree, and the
CLI must exit non-zero on a regression and honor baseline/stale/
write-baseline semantics.

The repo-wide runs carry the ``lint_knobs`` marker so ``-m 'not
lint_knobs'`` can skip them; plain tier-1 runs them (they are stdlib-only
and fast — no jax tracing in this rung).
"""

from __future__ import annotations

import os

import pytest

from bfs_tpu import knobs as reg
from bfs_tpu.analysis import Baseline
from bfs_tpu.analysis.core import SourceFile
from bfs_tpu.analysis.knob_rules import (
    check_docs,
    check_key_completeness,
    check_parsers,
    check_provenance,
    check_scope,
    readme_knob_rows,
)
from bfs_tpu.analysis.knobs import (
    analyze_knobs,
    render_knob_table,
    write_docs,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted({f.rule for f in findings})


def snippets_of(findings):
    return {f.snippet for f in findings}


def _src(code, path="fx.py"):
    return SourceFile(os.path.join(REPO, path), REPO, text=code)


def _knob(name, *, kind="enum", default="auto", parse=None, affects=(),
          scope="call", canary="bogus", journal_key=None):
    if parse is None:
        def parse(raw, _n=name):
            if raw not in ("auto", "on", "off"):
                raise ValueError(f"{_n}={raw!r}: not one of auto/on/off")
            return raw
    return reg.Knob(
        name=name, kind=kind, default=default, parse=parse,
        doc=f"fixture knob {name}", affects=frozenset(affects),
        scope=scope, canary=canary, journal_key=journal_key,
    )


def _table(*knobs_):
    return {k.name: k for k in knobs_}


# -------------------------------------------------------------- KNB001 --

def test_knb001_raw_read_spellings_trip_accessor_passes():
    table = _table(_knob("BFS_TPU_FX"))
    trip = _src(
        "import os\n"
        "from os import environ, getenv\n"
        "def f():\n"
        "    a = os.environ.get('BFS_TPU_FX', 'auto')\n"
        "    b = os.getenv('BFS_TPU_FX')\n"
        "    c = getenv('BFS_TPU_FX')\n"
        "    d = environ['BFS_TPU_FX']\n"
        "    return a, b, c, d\n"
    )
    found = check_provenance([trip], table)
    raw = [f for f in found if "bypasses the typed accessor" in f.message]
    assert len(raw) == 4
    assert all(f.rule == "KNB001" for f in raw)

    ok = _src(
        "from bfs_tpu import knobs\n"
        "def f():\n"
        "    return knobs.get('BFS_TPU_FX')\n"
    )
    assert check_provenance([ok], table) == []


def test_knb001_writes_and_nonliteral_reads_are_allowed():
    table = _table(_knob("BFS_TPU_FX"))
    src = _src(
        "import os\n"
        "from bfs_tpu import knobs\n"
        "def f(names):\n"
        "    os.environ['BFS_TPU_FX'] = '1'\n"       # write
        "    os.environ.setdefault('BFS_TPU_FX', '1')\n"  # write
        "    os.environ.pop('BFS_TPU_FX', None)\n"   # write
        "    del os.environ['BFS_TPU_FX']\n"         # write
        "    vals = [os.environ.get(n, '') for n in names]\n"  # non-literal
        "    return vals, knobs.get('BFS_TPU_FX')\n"
    )
    assert check_provenance([src], table) == []


def test_knb001_both_directions_unregistered_and_dead_row():
    table = _table(_knob("BFS_TPU_FX"), _knob("BFS_TPU_DEAD"))
    src = _src(
        "from bfs_tpu import knobs\n"
        "def f():\n"
        "    a = knobs.get('BFS_TPU_FX')\n"
        "    return a, knobs.raw('BFS_TPU_ROGUE')\n"
    )
    found = check_provenance([src], table)
    assert rules_of(found) == ["KNB001"]
    snips = snippets_of(found)
    # direction 1: accessor read of an unregistered name
    assert any("BFS_TPU_ROGUE" in f.message for f in found)
    # direction 2: a registered row with no read site is equally fatal
    assert "knb:BFS_TPU_DEAD:unread" in snips
    # the read knob itself is clean
    assert not any("BFS_TPU_FX" in s for s in snips)


def test_knb001_registry_module_is_exempt():
    table = _table(_knob("BFS_TPU_FX"))
    inside = _src(
        "import os\n"
        "def raw(name):\n"
        "    return os.environ.get('BFS_TPU_FX')\n",
        path="bfs_tpu/knobs.py",
    )
    reader = _src(
        "from bfs_tpu import knobs\n"
        "def f():\n"
        "    return knobs.get('BFS_TPU_FX')\n"
    )
    assert check_provenance([inside, reader], table) == []


def test_knb001_suppression_pragma_is_honored():
    table = _table(_knob("BFS_TPU_FX"))
    src = _src(
        "import os\n"
        "from bfs_tpu import knobs\n"
        "def f():\n"
        "    knobs.get('BFS_TPU_FX')\n"
        "    # bfs_tpu: ok KNB001\n"
        "    return os.environ.get('BFS_TPU_FX')\n"
    )
    assert check_provenance([src], table) == []


# -------------------------------------------------------------- KNB002 --

def test_knb002_both_directions_on_fixture_providers():
    table = _table(
        _knob("BFS_TPU_A", affects=("ir",)),
        _knob("BFS_TPU_B", affects=("ir",)),
    )
    # live tuple misses B (unkeyed) and carries C (undeclared)
    found = check_key_completeness(
        table, {"ir": ("BFS_TPU_A", "BFS_TPU_C")}
    )
    assert rules_of(found) == ["KNB002"]
    assert snippets_of(found) == {
        "knb:BFS_TPU_B:ir:unkeyed", "knb:BFS_TPU_C:ir:undeclared",
    }
    # near-miss: exact match is clean
    assert check_key_completeness(
        table, {"ir": ("BFS_TPU_A", "BFS_TPU_B")}
    ) == []


def test_knb002_unimportable_provider_is_knb000():
    table = _table(_knob("BFS_TPU_A", affects=("ir",)))
    found = check_key_completeness(
        table, {"ir": ("no.such.module", "_FLAVOR_ENV")}
    )
    assert rules_of(found) == ["KNB000"]
    assert snippets_of(found) == {"knb:ir:provider"}


@pytest.mark.lint_knobs
def test_knb002_live_registry_matches_live_key_builders():
    """The tentpole proof: the registry's ``affects`` declarations and
    the ACTUAL imported flavor tuples / journal keys / engine
    fingerprint env are the same sets, in both directions, for every
    domain."""
    assert check_key_completeness() == []


def test_journal_env_config_resume_semantics():
    """A default run and an explicit-default run must produce the same
    journal config (they resume each other); a changed knob forks it."""
    from bfs_tpu.resilience.journal import env_config

    def clean(env):
        for k in reg.KNOBS:
            env.pop(k, None)

    saved = {k: os.environ[k] for k in reg.KNOBS if k in os.environ}
    try:
        clean(os.environ)
        base = env_config()
        os.environ["BFS_TPU_DIRECTION"] = "auto"  # the registered default
        assert env_config() == base
        os.environ["BFS_TPU_DIRECTION"] = "pull"
        assert env_config() != base
    finally:
        clean(os.environ)
        os.environ.update(saved)


# -------------------------------------------------------------- KNB003 --

def test_knb003_import_time_read_of_call_knob_trips():
    table = _table(
        _knob("BFS_TPU_CALL", scope="call"),
        _knob("BFS_TPU_IMP", scope="import"),
    )
    src = _src(
        "from bfs_tpu import knobs\n"
        "BAD = knobs.get('BFS_TPU_CALL')\n"
        "OK = knobs.get('BFS_TPU_IMP')\n"
    )
    found = check_scope([src], table)
    assert rules_of(found) == ["KNB003"]
    assert len(found) == 1 and "BFS_TPU_CALL" in found[0].message

    near = _src(
        "from bfs_tpu import knobs\n"
        "def f():\n"
        "    return knobs.get('BFS_TPU_CALL')\n"
    )
    assert check_scope([near], table) == []


def test_knb003_read_inside_traced_region_trips():
    table = _table(_knob("BFS_TPU_CALL"))
    src = _src(
        "from bfs_tpu import knobs\n"
        "# bfs_tpu: hot traced\n"
        "def body(x):\n"
        "    return x + (knobs.get('BFS_TPU_CALL') == 'on')\n"
    )
    found = check_scope([src], table)
    assert rules_of(found) == ["KNB003"]
    assert "trace time" in found[0].message

    near = _src(  # hot but NOT traced: runtime read is fine
        "from bfs_tpu import knobs\n"
        "# bfs_tpu: hot\n"
        "def body(x):\n"
        "    return x + (knobs.get('BFS_TPU_CALL') == 'on')\n"
    )
    assert check_scope([near], table) == []


# -------------------------------------------------------------- KNB004 --

def test_knb004_both_directions_and_rendered_table_is_clean():
    table = _table(_knob("BFS_TPU_FX"), _knob("BFS_TPU_GONE"))
    readme = (
        "# fixture\n\n"
        "| Knob | Default |\n| --- | --- |\n"
        "| `BFS_TPU_FX` | `auto` |\n"
        "| `BFS_TPU_STALE` | `1` |\n"
    )
    found = check_docs(readme, table)
    assert rules_of(found) == ["KNB004"]
    assert snippets_of(found) == {
        "knb:BFS_TPU_GONE:undocumented", "knb:BFS_TPU_STALE:stale-row",
    }
    # the stale finding points at the offending row's line
    stale = [f for f in found if f.snippet.endswith("stale-row")][0]
    assert readme.splitlines()[stale.line - 1].startswith("| `BFS_TPU_STALE`")
    # near-miss: the generated table covers the whole fixture registry
    assert check_docs(render_knob_table(table), table) == []


def test_readme_row_parser_skips_separators_and_strips_backticks():
    rows = readme_knob_rows(
        "| Knob | x |\n| --- | --- |\n| `BFS_TPU_A` | 1 |\n"
        "| BFS_TPU_B | 2 |\n| not a knob | 3 |\n"
    )
    assert rows == {"BFS_TPU_A": 3, "BFS_TPU_B": 4}


def test_write_docs_bootstraps_markers_and_is_idempotent(tmp_path):
    root = tmp_path
    (root / "README.md").write_text("# repo\n\nbody text\n")
    assert write_docs(root=str(root)) is True
    text = (root / "README.md").read_text()
    assert "<!-- knob-table:begin -->" in text
    assert "body text" in text  # existing prose kept
    # every live knob got a row — KNB004 satisfied mechanically
    assert set(readme_knob_rows(text)) == set(reg.KNOBS)
    # second run: no drift, no rewrite
    assert write_docs(root=str(root)) is False
    # a hand-edited table region is regenerated in place, prose kept
    (root / "README.md").write_text(text.replace(
        "<!-- knob-table:begin -->",
        "<!-- knob-table:begin -->\n| `BFS_TPU_STALE` | x |", 1))
    assert write_docs(root=str(root)) is True
    assert "BFS_TPU_STALE" not in (root / "README.md").read_text()


# -------------------------------------------------------------- KNB005 --

def test_knb005_default_and_canary_roundtrip_fixture():
    def picky(raw):
        if raw != "auto":
            raise ValueError("nope")  # does not name the knob
        return raw

    table = _table(
        _knob("BFS_TPU_OK"),
        _knob("BFS_TPU_BAD_DEFAULT", default="zap"),
        _knob("BFS_TPU_LOOSE", parse=lambda raw: raw),  # accepts canary
        _knob("BFS_TPU_NO_CANARY", canary=None),
        _knob("BFS_TPU_FREEFORM", kind="path", parse=lambda raw: raw,
              canary=None),
    )
    found = check_parsers(table)
    assert rules_of(found) == ["KNB005"]
    assert snippets_of(found) == {
        "knb:BFS_TPU_BAD_DEFAULT:default-rejected",
        "knb:BFS_TPU_LOOSE:canary-accepted",
        "knb:BFS_TPU_NO_CANARY:no-canary",
    }


@pytest.mark.lint_knobs
def test_knb005_live_registry_roundtrips():
    assert check_parsers() == []


def test_knob_error_names_the_var_for_operators():
    with pytest.raises(reg.KnobError) as exc:
        reg.parse_value("BFS_TPU_DIRECTION", "sideways")
    assert "BFS_TPU_DIRECTION" in str(exc.value)
    assert exc.value.knob == "BFS_TPU_DIRECTION"


def test_registry_defaults_match_module_constants():
    """The migration's no-behavior-change pin: the registry defaults
    must equal the module constants the hand-rolled reads used to
    fall back to."""
    from bfs_tpu.models.direction import DEFAULT_ALPHA, DEFAULT_BETA
    from bfs_tpu.parallel.exchange import DEFAULT_BUDGET_DIV
    from bfs_tpu.resilience.superstep_ckpt import DEFAULT_MTBF_S
    from bfs_tpu.ops import relay_pallas

    assert reg.get("BFS_TPU_DIRECTION_ALPHA") == DEFAULT_ALPHA
    assert reg.get("BFS_TPU_DIRECTION_BETA") == DEFAULT_BETA
    assert reg.get("BFS_TPU_EXCHANGE_DIV") == DEFAULT_BUDGET_DIV
    assert reg.get("BFS_TPU_CKPT_MTBF_S") == DEFAULT_MTBF_S
    # import-scoped kernel geometry: the module constants ARE the
    # accessor reads now; they must agree with the registry defaults
    # when the env is unset (tier-1 never sets them).
    if "BFS_TPU_TILE_ROWS" not in os.environ:
        assert relay_pallas.TILE_ROWS == reg.parse_value(
            "BFS_TPU_TILE_ROWS", reg.KNOBS["BFS_TPU_TILE_ROWS"].default)
    if "BFS_TPU_OUTER_TT" not in os.environ:
        assert relay_pallas.OUTER_TT == reg.parse_value(
            "BFS_TPU_OUTER_TT", reg.KNOBS["BFS_TPU_OUTER_TT"].default)


# ------------------------------------------------- repo-wide + caching --

@pytest.mark.lint_knobs
def test_repo_knob_self_lint_is_clean():
    """The whole contract holds on the shipped tree: no raw reads, no
    dead rows, complete keys, clean scopes, full docs, round-tripping
    parsers — with zero baseline entries needed."""
    findings, meta = analyze_knobs(use_cache=False)
    assert findings == []
    assert meta["skipped"] == {}
    assert len(meta["knobs"]) == len(reg.KNOBS)


@pytest.mark.lint_knobs
def test_knob_result_cache_miss_then_hit(tmp_path):
    f1, m1 = analyze_knobs(cache_dir=str(tmp_path))
    assert m1["cache"] == "miss"
    f2, m2 = analyze_knobs(cache_dir=str(tmp_path))
    assert m2["cache"] == "hit"
    assert [f.snippet for f in f2] == [f.snippet for f in f1]
    assert m2["knobs"] == m1["knobs"]


def test_fixture_overrides_disable_cache(tmp_path):
    _, meta = analyze_knobs(
        _table(_knob("BFS_TPU_FX")), cache_dir=str(tmp_path)
    )
    assert meta["cache"] == "off"
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------------------------------ CLI --

def _poison(monkeypatch):
    """Register a knob nothing reads or documents: the live pass must
    fail with the dead-row and undocumented findings."""
    monkeypatch.setitem(
        reg.KNOBS, "BFS_TPU_KNBTEST_GHOST", _knob("BFS_TPU_KNBTEST_GHOST")
    )


@pytest.mark.lint_knobs
def test_cli_knobs_exits_nonzero_on_regression(monkeypatch, capsys):
    from bfs_tpu.analysis import __main__ as cli

    _poison(monkeypatch)
    rc = cli.main(["--knobs", "--no-cache", "--no-baseline"])
    out = capsys.readouterr()
    assert rc == 1
    assert "KNB001" in out.out and "KNB004" in out.out
    assert "BFS_TPU_KNBTEST_GHOST" in out.out


@pytest.mark.lint_knobs
def test_cli_knobs_subcommand_and_baseline_accept(monkeypatch, tmp_path,
                                                  capsys):
    from bfs_tpu.analysis import __main__ as cli

    _poison(monkeypatch)
    findings, _ = analyze_knobs(use_cache=False)
    bl = tmp_path / "baseline.txt"
    bl.write_text(Baseline.render(findings, "fixture ghost knob"))
    rc = cli.main(["knobs", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0
    assert "baseline-accepted" in out.err


@pytest.mark.lint_knobs
def test_cli_stale_knb_entry_fails_default_surface(tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli

    bl = tmp_path / "baseline.txt"
    bl.write_text("KNB001  deadbeefdead  [bfs_tpu/knobs.py:0] gone\n")
    rc = cli.main(["--knobs", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 1
    assert "STALE" in out.err


@pytest.mark.lint_knobs
def test_cli_knobs_write_baseline_prints_never_clobbers(monkeypatch,
                                                        tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli

    _poison(monkeypatch)
    bl = tmp_path / "baseline.txt"
    bl.write_text("# hand-curated\n")
    rc = cli.main(["--knobs", "--no-cache", "--write-baseline",
                   "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0
    assert "KNB finding(s) rendered above" in out.err
    assert bl.read_text() == "# hand-curated\n"  # not clobbered
    assert "KNB001" in out.out  # candidates printed for curation


def test_cli_knobs_rejects_scoping_and_orphan_write_docs(capsys):
    from bfs_tpu.analysis import __main__ as cli

    assert cli.main(["--knobs", "bfs_tpu/models/bfs.py"]) == 2
    assert cli.main(["--knobs", "--changed"]) == 2
    assert cli.main(["--knobs", "--ir"]) == 2
    assert cli.main(["--write-docs"]) == 2
    capsys.readouterr()


@pytest.mark.lint_knobs
def test_cli_write_docs_green_and_json_meta(tmp_path, capsys):
    """--write-docs regenerates (here: confirms current) the README
    table, then the pass runs green; --json carries the knob meta."""
    from bfs_tpu.analysis import __main__ as cli

    rc = cli.main(["--knobs", "--no-cache", "--write-docs", "--json"])
    out = capsys.readouterr()
    assert rc == 0
    assert "already current" in out.err
    import json as _json

    doc = _json.loads(out.out)
    assert doc["findings"] == []
    assert doc["ir"]["knobs"]  # meta payload rides the shared key
