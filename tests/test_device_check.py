"""On-device check() tests (ISSUE 2 tentpole c): parity with the host
parity-oracle ``check()`` on tinyCG/randomG — including deliberately
corrupted state — and the transfer-free property (verification pulls a
counter vector, never dist/parent arrays)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bfs_tpu.graph.csr import INF_DIST, NO_PARENT
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.oracle.device import COUNT_FIELDS, DeviceChecker


def _agree(graph, dist, parent, sources):
    """Host check() and the device verdict must agree on validity."""
    host = check(graph, dist, parent, sources)
    dev = DeviceChecker.from_graph(graph).check(
        jnp.asarray(dist), jnp.asarray(parent), sources
    )
    assert (host == []) == (dev == {}), (host, dev)
    return host, dev


@pytest.mark.parametrize("sources", [0, 3, [0, 3]])
def test_parity_valid_results_tiny(tiny_graph, sources):
    for bfs_fn in (queue_bfs, canonical_bfs):
        dist, parent = bfs_fn(tiny_graph, sources)
        host, dev = _agree(tiny_graph, dist, parent, sources)
        assert host == [] and dev == {}


def test_parity_valid_results_medium(medium_graph):
    dist, parent = canonical_bfs(medium_graph, 0)
    host, dev = _agree(medium_graph, dist, parent, 0)
    assert host == [] and dev == {}


def test_corrupted_parent_detected(medium_graph):
    dist, parent = queue_bfs(medium_graph, 0)
    bad = parent.copy()
    # Point a reached non-source vertex at a non-neighbour: the classic
    # "plausible-looking wrong parent" a broken slot mapping would produce.
    reached = np.flatnonzero((dist != INF_DIST) & (dist > 0))
    w = int(reached[-1])
    non_neighbours = np.setdiff1d(
        np.arange(medium_graph.num_vertices), medium_graph.adj(w)
    )
    bad[w] = int(non_neighbours[non_neighbours != w][0])
    host, dev = _agree(medium_graph, dist, bad, 0)
    assert host != [] and dev  # both flag it
    assert "tree_edge_missing" in dev or "tree_dist_mismatch" in dev


def test_parentless_reached_vertex_detected(tiny_graph):
    dist, parent = queue_bfs(tiny_graph, 0)
    bad = parent.copy()
    w = int(np.flatnonzero(dist == 1)[0])
    bad[w] = NO_PARENT
    host, dev = _agree(tiny_graph, dist, bad, 0)
    assert host != [] and dev.get("reached_without_parent") == 1


def test_corrupted_dist_detected(medium_graph):
    dist, parent = queue_bfs(medium_graph, 0)
    bad = dist.copy()
    w = int(np.flatnonzero(dist == 1)[0])
    bad[w] = 7  # breaks the triangle inequality and the tree relation
    host, dev = _agree(medium_graph, bad, parent, 0)
    assert host != [] and dev


def test_source_distance_invariant(tiny_graph):
    dist, parent = queue_bfs(tiny_graph, 0)
    bad = dist.copy()
    bad[0] = 1
    _, dev = _agree(tiny_graph, bad, parent, 0)
    assert dev.get("source_dist_nonzero") == 1


def test_coverage_mismatch_counts_bits(tiny_graph):
    dist, _ = queue_bfs(tiny_graph, 0)
    dc = DeviceChecker.from_graph(tiny_graph)
    ref = dc.packed_reached(jnp.asarray(dist))
    assert dc.coverage_mismatch(jnp.asarray(dist), ref) == 0
    other = dist.copy()
    other[4] = INF_DIST
    assert dc.coverage_mismatch(jnp.asarray(other), ref) == 1


def test_transfer_free_verification(monkeypatch, medium_graph):
    """The whole point: verifying a result transfers COUNTERS, never the
    dist/parent arrays.  Asserted by intercepting jax.device_get — every
    pull during check()/coverage_mismatch must be a few elements."""
    dist, parent = canonical_bfs(medium_graph, 0)
    dc = DeviceChecker.from_graph(medium_graph)
    dist_d, parent_d = jnp.asarray(dist), jnp.asarray(parent)
    ref = dc.packed_reached(dist_d)

    pulled_sizes = []
    real_device_get = jax.device_get

    def spying_device_get(x):
        for leaf in jax.tree_util.tree_leaves(x):
            pulled_sizes.append(int(np.asarray(getattr(leaf, "size", 1))))
        return real_device_get(x)

    monkeypatch.setattr(jax, "device_get", spying_device_get)
    verdict = dc.check(dist_d, parent_d, 0)
    mismatch = dc.coverage_mismatch(dist_d, ref)
    monkeypatch.undo()
    assert verdict == {} and mismatch == 0
    assert pulled_sizes, "verification must have pulled the verdicts"
    assert max(pulled_sizes) <= len(COUNT_FIELDS), pulled_sizes


def test_relay_engine_to_original_device_parity(medium_graph):
    """RelayEngine.to_original_device must match the host-side mapping
    bit-for-bit, and its output must satisfy the on-device verifier."""
    from bfs_tpu.graph import benes

    if not benes.native_available():
        pytest.skip("requires the native benes router")
    from bfs_tpu.models.bfs import RelayEngine

    eng = RelayEngine(medium_graph)
    source = 0
    state = eng.run_many_device([source])[0]
    dist_d, parent_d = eng.to_original_device(state, source)
    res = eng.run(source)
    np.testing.assert_array_equal(np.asarray(dist_d), res.dist)
    np.testing.assert_array_equal(np.asarray(parent_d), res.parent)
    dc = DeviceChecker.from_graph(medium_graph)
    assert dc.check(dist_d, parent_d, source) == {}
    assert check(medium_graph, res.dist, res.parent, source) == []
