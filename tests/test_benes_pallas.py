"""v4 fused-pass Pallas kernels vs the XLA per-stage reference.

Runs in Pallas interpret mode on the CPU test platform: same kernel code
path as the TPU (minus Mosaic lowering), bit-exact against apply_benes_std.
The real-TPU compiled path is additionally exercised by the bench's check()
invariants (bfs_tpu/bench.py) on every benchmark run.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Exercise the lane-compacted pass-B path (opt-in on real runs — slower in
# fast-DMA windows, kept for DMA-starved ones; see relay_pallas).
os.environ["BFS_TPU_LANE_COMPACT"] = "1"

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bfs_tpu.graph import benes  # noqa: E402

if not benes.native_available():  # pragma: no cover
    pytest.skip("native benes router unavailable", allow_module_level=True)

import jax.numpy as jnp  # noqa: E402

from bfs_tpu.graph.relay import _compact_and_table  # noqa: E402
from bfs_tpu.ops.relay import apply_benes_std, pack_std, unpack_std  # noqa: E402
from bfs_tpu.ops.relay_pallas import (  # noqa: E402
    apply_benes_fused,
    pass_static,
    prepare_pass_masks,
)


@pytest.mark.parametrize("tile_rows", [16, 64])
def test_fused_passes_match_xla(tile_rows):
    """All three fused passes (outer prefix, local run, outer suffix) with
    compacted masks and tail-range skips route exactly perm."""
    rng = np.random.default_rng(5)
    n = 1 << 19  # r = 128 rows; tile_rows < r forces outer passes
    perm = rng.permutation(n).astype(np.int64)
    masks, table = _compact_and_table(benes.route_std(perm), n)
    ps = pass_static(table, n, tile_rows=tile_rows)
    arrays = [
        jnp.asarray(a)
        for a in prepare_pass_masks(masks, table, n, tile_rows=tile_rows)
    ]
    from bfs_tpu.ops.relay_pallas import _is_lane_compact

    # 3 passes; +1 array when the local pass lane-compacts any stage (the
    # lane64 side array is emitted right after the local array).
    local_specs = next(sp for m, _t, _tt, sp in ps if m == "local")
    n_lane = 1 if any(_is_lane_compact(st) for st in local_specs) else 0
    assert len(ps) == 3 and len(arrays) == 3 + n_lane
    assert n_lane == 1  # d=2^9..2^11 stages exist at n=2^19
    bits = rng.integers(0, 2, size=n).astype(np.uint8)
    x = pack_std(jnp.asarray(bits))
    want = np.asarray(
        unpack_std(apply_benes_std(x, jnp.asarray(masks), table, n), n)
    )
    got_x = apply_benes_fused(x, arrays, ps, n, interpret=True)
    got = np.asarray(unpack_std(got_x, n))
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, bits[perm])


def test_fused_identity_tail_skips_are_correct():
    """A permutation with a large identity tail: with live <= n/2 the pad
    pairs are pure and route switch-free, so stages carry skippable nonzero
    ranges; the guarded DMA/compute path must still route exactly."""
    rng = np.random.default_rng(6)
    n = 1 << 19
    live = n * 3 // 8
    perm = np.arange(n, dtype=np.int64)
    perm[:live] = rng.permutation(live)
    masks, table = _compact_and_table(benes.route_std(perm), n)
    # the tail must actually produce skippable ranges
    assert any(st.hi < st.nwords for st in table)
    ps = pass_static(table, n, tile_rows=16)
    arrays = [
        jnp.asarray(a) for a in prepare_pass_masks(masks, table, n, tile_rows=16)
    ]
    bits = rng.integers(0, 2, size=n).astype(np.uint8)
    x = pack_std(jnp.asarray(bits))
    x = apply_benes_fused(x, arrays, ps, n, interpret=True)
    np.testing.assert_array_equal(np.asarray(unpack_std(x, n)), bits[perm])


def test_apply_benes_fused_end_to_end():
    rng = np.random.default_rng(7)
    n = 1 << 19
    perm = rng.permutation(n).astype(np.int64)
    masks, table = _compact_and_table(benes.route_std(perm), n)
    ps = pass_static(table, n, tile_rows=32)
    arrays = [
        jnp.asarray(a) for a in prepare_pass_masks(masks, table, n, tile_rows=32)
    ]
    bits = rng.integers(0, 2, size=n).astype(np.uint8)
    out = apply_benes_fused(
        pack_std(jnp.asarray(bits)), arrays, ps, n, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(unpack_std(out, n)), bits[perm])


def test_elem_fused_passes_match_reference():
    """Element-major fused passes (uint32 per element, vertically-packed
    masks) route exactly perm on whole uint32 payloads, both groups."""
    import jax.numpy as jnp

    from bfs_tpu.ops.relay_elem import apply_benes_elem
    from bfs_tpu.ops.relay_pallas import (
        _run_elem_pass,
        elem_pass_static,
        prepare_elem_pass_masks,
    )

    rng = np.random.default_rng(9)
    n = 1 << 16
    perm = rng.permutation(n).astype(np.int64)
    masks, table = _compact_and_table(benes.route_std(perm), n)
    ps = elem_pass_static(table, n, tile_rows=128, outer_tt=32)
    arrays = [
        jnp.asarray(a)
        for a in prepare_elem_pass_masks(masks, table, n, tile_rows=128,
                                         outer_tt=32)
    ]
    assert [m[0] for m in ps] == ["outer", "local", "outer"]
    x = rng.integers(0, 2**32, (2, n), dtype=np.uint32)
    want = np.asarray(
        apply_benes_elem(jnp.asarray(x), jnp.asarray(masks), table, n)
    )
    np.testing.assert_array_equal(want, x[:, perm])
    got = jnp.asarray(x)
    for (mode, tr, tt, specs), arr in zip(ps, arrays):
        got = _run_elem_pass(got, arr, mode, tr, tt, specs, n, True)
    np.testing.assert_array_equal(np.asarray(got), want)
