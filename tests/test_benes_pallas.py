"""Fused Beneš Pallas passes vs the per-stage XLA path and an element-space
NumPy reference.

apply_benes_fused (ops/benes_pallas.py) must be bit-exact with applying the
same stages one butterfly at a time.  Runs under the Pallas interpreter so
the CPU test platform covers the kernel math (including the mask DMA
streaming); the real-TPU compiled path is exercised by bench.py, whose
result is check()-verified.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from bfs_tpu.ops.benes_pallas import (  # noqa: E402
    LANES,
    apply_benes_fused,
    local_stage_run,
    stage_distances,
)
from bfs_tpu.ops.relay import pack_bits_host  # noqa: E402


def _unpack_host(words: np.ndarray, n: int) -> np.ndarray:
    nw = max(n // 32, 1)
    out = np.zeros(n, dtype=np.uint8)
    for b in range(32):
        out[b * nw : (b + 1) * nw] = (words >> np.uint32(b)) & 1
    return out


def _butterfly_elements(x: np.ndarray, mask_bits: np.ndarray, d: int) -> np.ndarray:
    """One stage in element space: swap pairs (e, e+d) where the mask bit at
    the LOWER element is set (matches ops/relay._apply_benes_small)."""
    x2 = x.reshape(-1, 2, d).copy()
    m = mask_bits.reshape(-1, 2, d)[:, 0, :].astype(bool)
    lo, hi = x2[:, 0, :].copy(), x2[:, 1, :].copy()
    x2[:, 0, :] = np.where(m, hi, lo)
    x2[:, 1, :] = np.where(m, lo, hi)
    return x2.reshape(-1)


def test_pack_unpack_kernels_roundtrip():
    from bfs_tpu.ops.benes_pallas import pack_bits_pallas, unpack_bits_pallas

    n = 1 << 20
    rng = np.random.default_rng(3)
    bits = rng.integers(0, 2, size=n, dtype=np.uint8)
    words = pack_bits_host(bits, n)
    got_w = np.asarray(pack_bits_pallas(jnp.asarray(bits), n, interpret=True))
    np.testing.assert_array_equal(got_w, words)
    got_b = np.asarray(unpack_bits_pallas(jnp.asarray(words), n, interpret=True))
    np.testing.assert_array_equal(got_b, bits)


@pytest.mark.parametrize(
    "n,tile_rows",
    [
        (1 << 15, 4),   # r=8: outer passes carry the bit stages + big rolls
        (1 << 16, 8),   # r=16
        (1 << 16, 16),  # tr == r: outer passes carry ONLY bit-plane stages
    ],
)
def test_fused_passes_match_element_reference(n, tile_rows):
    rng = np.random.default_rng(7)
    dists = stage_distances(n)
    # Mask contract (native/benes.cpp): swap bits sit ONLY at the lower
    # element of each pair — the bit-plane stage formula relies on it.
    lower = [np.asarray((np.arange(n) & d) == 0, dtype=np.uint8) for d in dists]
    masks = np.stack(
        [pack_bits_host(rng.integers(0, 2, size=n, dtype=np.uint8) & lw, n)
         for lw in lower]
    )
    xbits = rng.integers(0, 2, size=n, dtype=np.uint8)
    xwords = pack_bits_host(xbits, n)

    lo, hi = local_stage_run(n, tile_rows)
    assert hi > lo
    if tile_rows < n // 32 // LANES:
        assert lo > 0 and hi < len(dists)  # all three passes exercised

    got = np.asarray(
        apply_benes_fused(
            jnp.asarray(xwords), jnp.asarray(masks), n=n,
            tile_rows=tile_rows, interpret=True,
        )
    )

    ref = xbits.copy()
    for s, d in enumerate(dists):
        ref = _butterfly_elements(ref, _unpack_host(masks[s], n), d)
    np.testing.assert_array_equal(got, pack_bits_host(ref, n))
