"""Hash-routed serve fleet smoke tests (ISSUE 20) — the `fleet_smoke`
ci-gate stage.

Two in-process replicas behind a :class:`FleetRouter` sharing one
on-disk layout/label cache: deterministic primary routing, the
sequential rolling register (replica 0 pays the build, replica 1
warm-hits the sidecar), a mid-load epoch swap, an induced replica
failure (the server is CLOSED directly, exercising the
completion-time failover path, not the kill_replica bookkeeping), and
the breaker/NoReplicaAvailable terminal states — with every routed
answer checked against the host oracle throughout.
"""

import os

import numpy as np
import pytest

from bfs_tpu.cache.layout import LayoutCache
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.oracle.bfs import queue_bfs
from bfs_tpu.serve import FleetRouter, NoReplicaAvailable

pytestmark = pytest.mark.fleet_smoke

TIMEOUT = 300
G = "fleet-g"


@pytest.fixture(scope="module")
def fleet_graph():
    return gnm_graph(150, 400, seed=11)


@pytest.fixture()
def fleet(fleet_graph, tmp_path):
    os.environ["BFS_TPU_LABELS"] = "6"
    try:
        rt = FleetRouter(
            replicas=2, layout_cache=LayoutCache(tmp_path), max_batch=8
        )
        rt.register(G, fleet_graph)
    finally:
        os.environ.pop("BFS_TPU_LABELS", None)
    with rt:
        yield rt


def _truth(graph, cache, u):
    if u not in cache:
        cache[u] = queue_bfs(graph, int(u))[0]
    return cache[u]


def test_rolling_register_shares_sidecar(fleet):
    """Replica 0 pays the label build; replica 1 warm-hits the shared
    content-addressed bundle — the no-thundering-herd contract."""
    counters = [
        srv.metrics.report()["counters"] for srv in fleet.servers
    ]
    assert counters[0].get("label_builds", 0) == 1
    assert counters[0].get("label_build_cache_misses", 0) == 1
    assert counters[1].get("label_builds", 0) == 1
    assert counters[1].get("label_build_cache_hits", 0) == 1
    assert fleet.metrics.report()["counters"]["router_rolling_registers"] == 2


def test_routing_is_deterministic(fleet):
    for s in (0, 7, 42):
        assert fleet._ring(G, [s]) == fleet._ring(G, [s])
    assert {fleet._ring(G, [s])[0] for s in range(32)} == {0, 1}


def test_full_and_point_queries_oracle_exact(fleet, fleet_graph):
    cache = {}
    rng = np.random.default_rng(0)
    v = fleet_graph.num_vertices
    for s in rng.integers(0, v, size=6):
        reply = fleet.query(G, int(s)).result(TIMEOUT)
        np.testing.assert_array_equal(
            np.asarray(reply.dist), _truth(fleet_graph, cache, int(s))
        )
    for u, w in rng.integers(0, v, size=(8, 2)):
        reply = fleet.query_dist(G, int(u), int(w)).result(TIMEOUT)
        assert reply.dist == int(_truth(fleet_graph, cache, int(u))[w])


def test_epoch_swap_under_load_stays_exact(fleet, fleet_graph):
    cache = {}
    v = fleet_graph.num_vertices
    futs = [fleet.query_dist(G, u, (u * 7 + 3) % v) for u in range(8)]
    os.environ["BFS_TPU_LABELS"] = "6"
    try:
        fleet.register(G, fleet_graph)  # rolling epoch bump mid-flight
    finally:
        os.environ.pop("BFS_TPU_LABELS", None)
    futs += [fleet.query_dist(G, u, (u * 5 + 1) % v) for u in range(8)]
    for i, f in enumerate(futs):
        reply = f.result(TIMEOUT)
        want = int(_truth(fleet_graph, cache, reply.u)[reply.v])
        assert reply.dist == want, f"query {i} wrong across the swap"
    assert (
        fleet.metrics.report()["counters"]["router_rolling_registers"] == 4
    )


def test_failover_on_closed_replica(fleet, fleet_graph):
    """Close one replica DIRECTLY (no router bookkeeping): queries whose
    primary it was must fail over to the survivor and stay exact."""
    cache = {}
    v = fleet_graph.num_vertices
    victim = fleet._ring(G, [0, 1])[0]
    fleet.servers[victim].close()
    reply = fleet.query_dist(G, 0, 1).result(TIMEOUT)
    assert reply.dist == int(_truth(fleet_graph, cache, 0)[1])
    # Keep hammering: every source routes somewhere and every answer is
    # exact, whichever side of the ring it lands on.
    for s in range(10):
        reply = fleet.query(G, s % v).result(TIMEOUT)
        np.testing.assert_array_equal(
            np.asarray(reply.dist), _truth(fleet_graph, cache, s % v)
        )
    c = fleet.metrics.report()["counters"]
    assert c.get("router_failovers", 0) >= 1


def test_kill_replica_routes_around(fleet, fleet_graph):
    cache = {}
    fleet.kill_replica(1)
    assert fleet.alive() == [0]
    for s in (3, 90):
        reply = fleet.query(G, s).result(TIMEOUT)
        np.testing.assert_array_equal(
            np.asarray(reply.dist), _truth(fleet_graph, cache, s)
        )
    c = fleet.metrics.report()["counters"]
    assert c.get("router_replicas_killed", 0) == 1


def test_all_replicas_dead_raises(fleet):
    fleet.kill_replica(0)
    fleet.kill_replica(1)
    with pytest.raises(NoReplicaAvailable):
        fleet.query(G, 0)
