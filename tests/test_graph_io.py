"""Ingest layer tests: Sedgewick parsing, bi-directing, CSR, device padding.

Covers GraphFileUtil.convert behavior (GraphFileUtil.java:45-69) and algs4
Graph construction (Graph.java:85-94,145-172)."""

import os

import numpy as np
import pytest

from bfs_tpu.graph.csr import Graph, build_device_graph, reshard
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.graph.io import parse_sedgewick, read_snap_edge_list, write_sedgewick

from conftest import TINY_TEXT, TINY_V, TINY_EDGES


def test_parse_sedgewick_tiny(tiny_graph):
    g = parse_sedgewick(TINY_TEXT)
    assert g.num_vertices == TINY_V
    # Undirected input is bi-directed: every edge twice (GraphFileUtil.java:64-65).
    assert g.num_edges == 2 * len(TINY_EDGES)
    np.testing.assert_array_equal(g.src, tiny_graph.src)
    np.testing.assert_array_equal(g.dst, tiny_graph.dst)


def test_adjacency_and_degree(tiny_graph):
    # Sorted adjacency view (Graph.adj / Graph.degree parity).
    assert list(tiny_graph.adj(0)) == [1, 2, 5]
    assert list(tiny_graph.adj(3)) == [2, 4, 5]
    assert tiny_graph.degree(2) == 4
    assert tiny_graph.degree(4) == 2


def test_csr_roundtrip_counts(tiny_graph):
    indptr, indices = tiny_graph.csr()
    assert indptr[-1] == tiny_graph.num_edges
    assert indices.shape[0] == tiny_graph.num_edges


def test_parse_rejects_truncated():
    with pytest.raises(ValueError):
        parse_sedgewick("6\n8\n0 5\n")


def test_write_read_roundtrip(tmp_path, tiny_graph):
    p = tmp_path / "g.txt"
    write_sedgewick(tiny_graph, p)
    g2 = parse_sedgewick(p.read_text())
    assert g2.num_vertices == tiny_graph.num_vertices
    assert sorted(zip(g2.src.tolist(), g2.dst.tolist())) == sorted(
        zip(tiny_graph.src.tolist(), tiny_graph.dst.tolist())
    )


def test_snap_reader(tmp_path):
    p = tmp_path / "snap.txt"
    p.write_text("# comment\n0\t1\n1\t2\n")
    g = read_snap_edge_list(p)
    assert g.num_vertices == 3
    assert g.num_edges == 4  # bi-directed


def test_snap_round_trip(tmp_path):
    """SNAP writer -> reader round-trip with real-format comment headers,
    including the directed and num_vertices-override paths."""
    from bfs_tpu.graph.io import write_snap_edge_list

    rng = np.random.default_rng(7)
    pairs = rng.integers(0, 50, size=(200, 2), dtype=np.int64)
    p = tmp_path / "soc-test.txt"
    write_snap_edge_list(pairs, p, name="soc-test", num_vertices=60)
    text = p.read_text()
    assert text.startswith("#") and "Nodes: 60 Edges: 200" in text
    g = read_snap_edge_list(p, undirected=False, num_vertices=60)
    assert g.num_vertices == 60 and g.num_edges == 200
    got = np.stack([g.src, g.dst], 1)
    np.testing.assert_array_equal(
        got[np.lexsort(got.T)], pairs[np.lexsort(pairs.T)].astype(np.int32)
    )


def test_snap_shape_generator_matches_target_shape():
    """snap_shape_edges hits an arbitrary (non-pow2) V/E shape with R-MAT
    degree skew (BASELINE.json config 4 synthesis path)."""
    from bfs_tpu.graph.generators import snap_shape_edges

    v, e = 1000, 12345
    pairs = snap_shape_edges(v, e, seed=4)
    assert pairs.shape == (e, 2)
    assert pairs.min() >= 0 and pairs.max() < v
    deg = np.bincount(pairs[:, 0], minlength=v)
    # Heavy tail: the top-1% hubs carry well more than a uniform share.
    top = np.sort(deg)[-v // 100 :].sum()
    assert top > 3 * e * 0.01


def test_device_graph_padding(tiny_graph):
    dg = build_device_graph(tiny_graph, block=64)
    assert dg.padded_edges % 64 == 0
    assert dg.num_edges == tiny_graph.num_edges
    pad = dg.src[dg.num_edges :]
    assert (pad == dg.sentinel).all()
    # dst-sorted for indices_are_sorted segment reductions.
    assert (np.diff(dg.dst) >= 0).all()


def test_device_graph_sharded(tiny_graph):
    dg = build_device_graph(tiny_graph, num_shards=4, block=8)
    assert dg.src.shape[0] == 4
    flat = dg.src.reshape(-1)
    assert (flat != dg.sentinel).sum() == tiny_graph.num_edges
    for s in range(4):
        assert (np.diff(dg.dst[s]) >= 0).all()
    dg2 = reshard(dg, 2, block=8)
    assert dg2.num_shards == 2
    assert (dg2.src.reshape(-1) != dg2.sentinel).sum() == tiny_graph.num_edges


def test_generators_shapes():
    g = rmat_graph(6, 4, seed=1)
    assert g.num_vertices == 64
    assert g.num_edges == 2 * 4 * 64
    g2 = gnm_graph(100, 300, seed=2)
    assert g2.num_edges == 600
    p = path_graph(10)
    assert p.num_edges == 18


def test_edge_out_of_range_rejected():
    with pytest.raises(ValueError):
        Graph.from_undirected_edges(3, np.array([[0, 3]]))


def test_write_read_preserves_multigraph():
    # Parallel edges and self-loops must round-trip exactly (multigraph
    # fidelity; algs4 Graph keeps multi-edges in its Bag).
    g = Graph.from_undirected_edges(3, np.array([[0, 1], [0, 1], [2, 2]]))
    import io as _io, tempfile, os as _os

    fd, p = tempfile.mkstemp()
    _os.close(fd)
    try:
        write_sedgewick(g, p)
        g2 = parse_sedgewick(open(p).read())
    finally:
        _os.unlink(p)
    assert g2.num_edges == g.num_edges
    assert sorted(zip(g2.src.tolist(), g2.dst.tolist())) == sorted(
        zip(g.src.tolist(), g.dst.tolist())
    )


def test_negative_edge_endpoint_rejected():
    with pytest.raises(ValueError):
        Graph.from_directed_edges(3, np.array([[0, -1]]))


REFERENCE_MEDIUM = "/root/reference/test-sets/mediumG.txt"


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_MEDIUM),
    reason="read-only reference mount not present",
)
def test_reference_mediumG_content_parity():
    """Parity against the REAL mediumG.txt (VERDICT r4 missing #4), gated
    on the reference mount: exact V/E, writer round-trip preserving the
    exact edge multiset, and the canonical oracle passing its own check()
    on the real file's content."""
    import tempfile

    from bfs_tpu.graph.io import read_sedgewick
    from bfs_tpu.oracle.bfs import canonical_bfs, check

    with open(REFERENCE_MEDIUM) as f:
        original_text = f.read()
    g = read_sedgewick(REFERENCE_MEDIUM)
    assert g.num_vertices == 250
    assert g.num_edges == 2 * 1273  # bi-directed undirected edges

    fd, p = tempfile.mkstemp()
    os.close(fd)
    try:
        write_sedgewick(g, p)
        with open(p) as f:
            written = f.read()
        g2 = parse_sedgewick(written)
    finally:
        os.unlink(p)
    # Header lines byte-identical; edge MULTISET identical (our writer
    # canonicalizes line order, so whole-file bytes are not comparable).
    assert written.split("\n")[:2] == original_text.split("\n")[:2]
    assert sorted(zip(g2.src.tolist(), g2.dst.tolist())) == sorted(
        zip(g.src.tolist(), g.dst.tolist())
    )

    dist, parent = canonical_bfs(g, 0)
    assert not check(g, dist, parent, 0)
