"""Batched multi-source BFS tests (BreadthFirstPaths.java:114-132 semantics
via collapse; per-source trees via the batch axis)."""

import numpy as np

from bfs_tpu.graph.generators import gnm_graph, path_graph
from bfs_tpu.models.bfs import bfs
from bfs_tpu.models.multisource import bfs_multi, collapse_multi_source
from bfs_tpu.oracle.bfs import check, queue_bfs


def test_batched_rows_match_single_runs(tiny_graph):
    sources = [0, 3, 5]
    res = bfs_multi(tiny_graph, sources)
    for i, s in enumerate(sources):
        single = bfs(tiny_graph, s)
        np.testing.assert_array_equal(res.dist[i], single.dist)
        np.testing.assert_array_equal(res.parent[i], single.parent)


def test_collapse_matches_oracle_multisource():
    g = path_graph(12)
    res = bfs_multi(g, [0, 11])
    dist, parent = collapse_multi_source(res)
    od, _ = queue_bfs(g, [0, 11])
    np.testing.assert_array_equal(dist, od)
    assert check(g, dist, parent, [0, 11]) == []


def test_collapse_random():
    for seed in range(3):
        g = gnm_graph(150, 400, seed=seed)
        srcs = [3, 77, 140]
        res = bfs_multi(g, srcs)
        dist, parent = collapse_multi_source(res)
        od, _ = queue_bfs(g, srcs)
        np.testing.assert_array_equal(dist, od)
        assert check(g, dist, parent, srcs) == []


def test_num_levels_is_max_over_sources():
    g = path_graph(10)
    res = bfs_multi(g, [0, 9])
    # Source 0 and 9 both need 9 relaxing supersteps + 1 empty terminator.
    assert res.num_levels == 10


def test_out_of_range_sources_rejected(tiny_graph):
    import pytest

    with pytest.raises(ValueError):
        bfs_multi(tiny_graph, [0, 6])


def test_multi_engines_bit_exact():
    """pull/push batched modes agree on dist AND parent; relay too when the
    native router is available (it maps relabeled results back)."""
    from bfs_tpu.graph.benes import native_available
    from bfs_tpu.graph.generators import rmat_graph

    g = rmat_graph(8, 6, seed=17)
    srcs = [0, 9, 33, 100]
    pull = bfs_multi(g, srcs, engine="pull")
    push = bfs_multi(g, srcs, engine="push")
    np.testing.assert_array_equal(pull.dist, push.dist)
    np.testing.assert_array_equal(pull.parent, push.parent)
    assert pull.num_levels == push.num_levels
    if native_available():
        relay = bfs_multi(g, srcs, engine="relay")
        np.testing.assert_array_equal(relay.dist, push.dist)
        np.testing.assert_array_equal(relay.parent, push.parent)
        assert relay.num_levels == push.num_levels


def test_device_resident_entry_points_match_host_results():
    """bfs_multi_device / RelayEngine.run_multi_device return the raw batched
    device state the benchmark harness times (sync = reading .level) —
    levels and reached sets must agree with the materialized results."""
    from bfs_tpu.graph.generators import rmat_graph
    from bfs_tpu.models.multisource import bfs_multi_device

    g = rmat_graph(8, 6, seed=17)
    srcs = [0, 9, 33]
    inf = np.iinfo(np.int32).max
    for engine in ("pull", "push"):
        host = bfs_multi(g, srcs, engine=engine)
        state, v = bfs_multi_device(g, srcs, engine=engine)
        assert v == g.num_vertices
        assert int(state.level) == host.num_levels
        np.testing.assert_array_equal(
            np.asarray(state.dist)[:, :v] != inf, host.dist != inf
        )

    from bfs_tpu.graph.benes import native_available

    if native_available():
        from bfs_tpu.models.bfs import RelayEngine

        eng = RelayEngine(g)
        host = eng.run_multi(srcs)
        state = eng.run_multi_device(srcs)
        assert int(state.level) == host.num_levels
        # device dist is in relabeled space (padded to vr >= V; dummies are
        # never reached) — reached COUNTS are permutation-invariant
        np.testing.assert_array_equal(
            (np.asarray(state.dist) != inf).sum(axis=1),
            (host.dist != inf).sum(axis=1),
        )
