"""Mesh-sharded engine tests on the 8-device virtual CPU platform — the
"N workers, one machine" methodology of the reference benchmark
(docs/BigData_Project.pdf §1.5), with shard counts 1/2/8 standing in for the
paper's 1/2/10 workers."""

import jax
import numpy as np
import pytest

from bfs_tpu.graph.csr import build_device_graph
from bfs_tpu.graph.generators import gnm_graph, rmat_graph
from bfs_tpu.models.bfs import bfs
from bfs_tpu.models.multisource import bfs_multi
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.parallel.sharded import bfs_sharded, bfs_sharded_multi, make_mesh


def test_virtual_device_count():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("engine", ["push", "pull"])
@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_sharded_matches_single_chip(tiny_graph, num_shards, engine):
    mesh = make_mesh(graph=num_shards)
    res = bfs_sharded(
        tiny_graph, 0, mesh=mesh, engine=engine, block=8, vertex_block_multiple=32
    )
    single = bfs(tiny_graph, 0)
    np.testing.assert_array_equal(res.dist, single.dist)
    np.testing.assert_array_equal(res.parent, single.parent)
    assert res.num_levels == single.num_levels


@pytest.mark.parametrize("engine", ["push", "pull"])
def test_sharded_random_graphs(engine):
    mesh = make_mesh(graph=8)
    for seed in range(3):
        g = gnm_graph(300, 900, seed=seed)
        res = bfs_sharded(
            g, 0, mesh=mesh, engine=engine, block=16, vertex_block_multiple=32
        )
        d, _ = queue_bfs(g, 0)
        _, p = canonical_bfs(g, 0)
        np.testing.assert_array_equal(res.dist, d)
        np.testing.assert_array_equal(res.parent, p)
        assert check(g, res.dist, res.parent, 0) == []


def test_sharded_rmat_prebuilt_device_graph():
    mesh = make_mesh(graph=4)
    g = rmat_graph(7, 4, seed=5)
    dg = build_device_graph(g, num_shards=4, block=32)
    res = bfs_sharded(dg, 0, mesh=mesh, engine="push")
    d, _ = queue_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)


def test_sharded_wrong_shard_count_rejected(tiny_graph):
    mesh = make_mesh(graph=4)
    dg = build_device_graph(tiny_graph, num_shards=2, block=8)
    with pytest.raises(ValueError):
        bfs_sharded(dg, 0, mesh=mesh, engine="push")


@pytest.mark.parametrize("engine", ["push", "pull"])
@pytest.mark.parametrize("batch,graph_shards", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_sharded_multi_source_2d_mesh(batch, graph_shards, engine):
    g = gnm_graph(200, 600, seed=9)
    mesh = make_mesh(graph=graph_shards, batch=batch)
    sources = list(range(8))  # divisible by every batch size used here
    res = bfs_sharded_multi(
        g, sources, mesh=mesh, engine=engine, block=16, vertex_block_multiple=32
    )
    ref = bfs_multi(g, sources)
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)


def test_sharded_multi_source_divisibility(tiny_graph):
    mesh = make_mesh(graph=2, batch=2)
    with pytest.raises(ValueError):
        bfs_sharded_multi(tiny_graph, [0, 1, 2], mesh=mesh, block=8)
