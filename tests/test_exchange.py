"""Compressed frontier exchange (ISSUE 11): arm parity + wire accounting.

The contract under test: every exchange arm (flat / bitmap / delta, and
auto's per-superstep density selection) ships DIFFERENT bytes but the
SAME frontier — dist, parent and the direction schedule must be
bit-identical across arms on every mesh size, including the >62-level
packed-cap fallback rerun.  Fixture shapes follow the direction suite:
an R-MAT (hubs spanning shards), a star (shallow, dense explosion) and a
path deeper than the packed cap.

Budget note: every (layout, mesh, arm) triple is one sharded XLA compile
on the 2-core container, so results AND schedules come from one
telemetry-carrying run each, layouts are built once per fixture, and the
full arm x mesh matrix runs on the R-MAT only (star at x2, the deep path
at x8 — the shapes that exercise what the smaller matrix cannot)."""

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.graph.relay import build_sharded_relay_graph
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.parallel.exchange import (
    EX_BITMAP,
    EX_DELTA,
    ExchangeConfig,
    exchange_report,
    resolve_exchange,
)
from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

pytestmark = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)

ARMS = ("flat", "bitmap", "delta", "auto")


def star_graph(n: int = 256):
    from bfs_tpu.graph.csr import Graph

    hub = np.zeros(n - 1, np.int32)
    leaves = np.arange(1, n, dtype=np.int32)
    return Graph(
        n, np.concatenate([hub, leaves]), np.concatenate([leaves, hub])
    )


def run_arm(srg, mesh, arm, s=0, direction="auto"):
    return bfs_sharded(
        srg, s, mesh=mesh, engine="relay", telemetry=True,
        direction=direction, exchange=arm,
    )


def assert_same(res_a, curve_a, res_b, curve_b):
    np.testing.assert_array_equal(res_a.dist, res_b.dist)
    np.testing.assert_array_equal(res_a.parent, res_b.parent)
    assert res_a.num_levels == res_b.num_levels
    assert (
        curve_a["direction_schedule"]["schedule"]
        == curve_b["direction_schedule"]["schedule"]
    )
    assert curve_a["occupancy"] == curve_b["occupancy"]


# ---------------------------------------------------------------------------
# Config / knob surface (no device work).
# ---------------------------------------------------------------------------

def test_resolve_exchange_env_knobs(monkeypatch):
    monkeypatch.setenv("BFS_TPU_EXCHANGE", "delta")
    monkeypatch.setenv("BFS_TPU_EXCHANGE_DIV", "4")
    cfg = resolve_exchange()
    assert (cfg.mode, cfg.budget_div) == ("delta", 4)
    assert resolve_exchange("flat").mode == "flat"  # argument wins
    monkeypatch.setenv("BFS_TPU_EXCHANGE", "zip")
    with pytest.raises(ValueError):
        resolve_exchange()
    monkeypatch.setenv("BFS_TPU_EXCHANGE", "auto")
    monkeypatch.setenv("BFS_TPU_EXCHANGE_DIV", "0")
    with pytest.raises(ValueError):
        resolve_exchange()


def test_delta_budget_sizing():
    # auto: ceil(kw/div); forced delta: the whole compact space (the
    # word-list arm must be able to ship ANY superstep).
    assert ExchangeConfig("auto", 8).delta_budget(64) == 8
    assert ExchangeConfig("auto", 8).delta_budget(3) == 1
    assert ExchangeConfig("delta", 8).delta_budget(64) == 64


def test_exchange_report_accounting():
    bacc = np.zeros(128, np.int64)
    aacc = np.zeros(128, np.int64)
    # levels 1..3: delta, bitmap, delta
    bacc[1], aacc[1] = 64, EX_DELTA
    bacc[2], aacc[2] = 256, EX_BITMAP
    bacc[3], aacc[3] = 64, EX_DELTA
    rep = exchange_report(
        bacc, aacc, ExchangeConfig("auto", 8), kw=8, nw=10, num_shards=8
    )
    assert rep["schedule"] == ["delta", "bitmap", "delta"]
    assert rep["bytes_per_level"] == [64, 256, 64]
    assert rep["total_bytes"] == 384
    # flat baseline: 3 executed levels x n * nw * 4 bytes
    assert rep["flat_total_bytes"] == 3 * 8 * 10 * 4
    assert rep["reduction_vs_flat"] == rep["flat_total_bytes"] / 384
    assert rep["delta_supersteps"] == 2 and rep["bitmap_supersteps"] == 1


# ---------------------------------------------------------------------------
# Arm parity: bit-identical results + schedules across arms and meshes.
# ---------------------------------------------------------------------------

def _arms_parity(g, meshes, arms):
    d_ref, _ = queue_bfs(g, 0)
    _, p_ref = canonical_bfs(g, 0)
    for n in meshes:
        srg = build_sharded_relay_graph(g, n)
        mesh = make_mesh(graph=n)
        base = None
        for arm in arms:
            res, curve = run_arm(srg, mesh, arm)
            np.testing.assert_array_equal(res.dist, d_ref)
            np.testing.assert_array_equal(res.parent, p_ref)
            assert check(g, res.dist, res.parent, 0) == []
            if base is None:
                base = (res, curve)
            else:
                assert_same(*base, res, curve)
            ex = curve["exchange"]
            assert ex["arm"] == arm
            assert len(ex["bytes_per_level"]) == len(ex["schedule"])
            assert ex["total_bytes"] == sum(ex["bytes_per_level"])
            if arm == "flat":
                assert set(ex["schedule"]) == {"flat"}
                assert ex["total_bytes"] == ex["flat_total_bytes"]
            else:
                assert "flat" not in ex["schedule"]
            if arm in ("bitmap", "auto"):
                # the sieved arms never exceed the flat baseline (forced
                # delta may: B = kw makes it a forcing/parity arm, not a
                # byte win)
                assert ex["total_bytes"] <= ex["flat_total_bytes"]


def test_rmat_arms_parity_x2():
    """Tier-1 core: all four arms, bit-identical, on the x2 mesh."""
    _arms_parity(rmat_graph(9, 8, seed=11), (2,), ARMS)


@pytest.mark.slow
def test_rmat_arms_parity_x1_x8():
    """The full mesh sweep (x1 degenerate collectives, x8 widest): every
    arm, same contract.  Slow lane: each (mesh, arm) is one sharded XLA
    compile on the 2-core container."""
    _arms_parity(rmat_graph(9, 8, seed=11), (1, 8), ARMS)


@pytest.mark.slow
def test_star_arms_parity_x2():
    g = star_graph(256)
    srg = build_sharded_relay_graph(g, 2)
    mesh = make_mesh(graph=2)
    outs = [run_arm(srg, mesh, arm, s=3) for arm in ("flat", "delta", "auto")]
    d, _ = queue_bfs(g, 3)
    np.testing.assert_array_equal(outs[0][0].dist, d)
    for res, curve in outs[1:]:
        assert_same(*outs[0], res, curve)


def test_deep_path_unpacked_fallback_x8():
    """>62 levels under sharding: the packed program exits on its level
    cap, the wrapper reruns unpacked, and the word-list arm stays
    bit-identical to the oracle through the whole fallback (the flat-arm
    twin of this run is in the slow sweep; arm-vs-arm equality at depth
    is covered there)."""
    g = path_graph(257)
    srg = build_sharded_relay_graph(g, 8)
    mesh = make_mesh(graph=8)
    d, p = queue_bfs(g, 0)
    res_d, curve_d = run_arm(srg, mesh, "delta")
    np.testing.assert_array_equal(res_d.dist, d)
    np.testing.assert_array_equal(res_d.parent, p)
    assert res_d.num_levels == 257
    # Forced delta sizes its budget at kw, so every superstep takes the
    # word-list branch at its static 2B-word payload.
    ex = curve_d["exchange"]
    assert set(ex["schedule"]) == {"delta"}
    # (levels beyond TEL_SLOTS clamp into the last accumulator slot, so
    # the final entry aggregates the >127-level tail — skip it)
    assert all(
        b == 8 * ex["budget_words"] * 4 * 2
        for b in ex["bytes_per_level"][:-1]
    )
    assert ex["supersteps"] == 257  # exact even past the slot clamp


@pytest.mark.slow
def test_deep_path_flat_parity_x8():
    """Flat-oracle twin of the deep-path fallback: bit-identical dist,
    parents, occupancy and direction schedule at 257 levels."""
    g = path_graph(257)
    srg = build_sharded_relay_graph(g, 8)
    mesh = make_mesh(graph=8)
    res_d, curve_d = run_arm(srg, mesh, "delta")
    res_f, curve_f = run_arm(srg, mesh, "flat")
    assert_same(res_d, curve_d, res_f, curve_f)


@pytest.mark.slow
def test_auto_arm_selects_by_density():
    """On a G(n,m) with a dense middle, auto must take delta on the
    sparse rim levels and fall back to bitmap only where the frontier
    outgrows the word-list budget — and the total must beat flat."""
    g = gnm_graph(1 << 10, 3 << 10, seed=5)
    deg = np.bincount(np.asarray(g.src), minlength=g.num_vertices)
    s = int(np.argmax(deg))
    srg = build_sharded_relay_graph(g, 8)
    mesh = make_mesh(graph=8)
    res_a, curve_a = run_arm(srg, mesh, "auto", s=s)
    res_f, curve_f = run_arm(srg, mesh, "flat", s=s)
    assert_same(res_a, curve_a, res_f, curve_f)
    ea = curve_a["exchange"]
    assert "delta" in ea["schedule"], ea["schedule"]
    assert ea["total_bytes"] < curve_f["exchange"]["total_bytes"]
