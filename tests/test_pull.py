"""Pull-mode (ELL gather/row-min) engine vs the oracle and the push engine."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import Graph, INF_DIST, build_device_graph
from bfs_tpu.graph.ell import build_pull_graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import bfs
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs


def _assert_matches_oracle(graph, source=0, **kwargs):
    result = bfs(graph, source, engine="pull", **kwargs)
    dist, parent = canonical_bfs(graph, source)
    np.testing.assert_array_equal(result.dist, dist)
    np.testing.assert_array_equal(result.parent, parent)
    assert check(graph, result.dist, result.parent, source) == []


def test_tiny_pull(tiny_graph):
    result = bfs(tiny_graph, 0, engine="pull")
    assert result.dist.tolist() == [0, 1, 1, 2, 2, 1]
    assert result.parent.tolist() == [0, 0, 0, 2, 2, 0]
    assert result.num_levels == 3


def test_pull_matches_push_and_oracle(tiny_graph):
    for seed in range(3):
        g = gnm_graph(200, 600, seed=seed)
        pull = bfs(g, 0, engine="pull")
        push = bfs(g, 0, engine="push")
        np.testing.assert_array_equal(pull.dist, push.dist)
        np.testing.assert_array_equal(pull.parent, push.parent)
        _assert_matches_oracle(g, 0)


def test_pull_rmat_with_hubs():
    # R-MAT is skewed: exercises multi-level folds.
    g = rmat_graph(9, 16, seed=5)
    pg = build_pull_graph(g, k=4)  # tiny k forces deep fold recursion
    assert len(pg.folds) >= 2
    result = bfs(pg, 0)
    dist, parent = canonical_bfs(g, 0)
    np.testing.assert_array_equal(result.dist, dist)
    np.testing.assert_array_equal(result.parent, parent)


def test_pull_path_graph_high_diameter():
    g = path_graph(50)
    _assert_matches_oracle(g, 0)
    r = bfs(g, 49, engine="pull")
    assert r.dist[0] == 49


def test_pull_disconnected():
    g = Graph.from_undirected_edges(5, np.array([[0, 1], [2, 3]]))
    r = bfs(g, 0, engine="pull")
    assert r.dist.tolist()[:2] == [0, 1]
    assert r.dist[2] == INF_DIST and r.dist[4] == INF_DIST
    assert r.parent[2] == -1


def test_pull_from_device_graph(tiny_graph):
    dg = build_device_graph(tiny_graph, block=16)
    result = bfs(dg, 0, engine="pull")
    assert result.dist.tolist() == [0, 1, 1, 2, 2, 1]


def test_pull_zero_edges():
    g = Graph.from_directed_edges(4, np.zeros((0, 2), dtype=np.int32))
    r = bfs(g, 2, engine="pull")
    assert r.dist[2] == 0
    assert all(r.dist[i] == INF_DIST for i in (0, 1, 3))


def test_pull_self_loops_and_multi_edges():
    g = Graph.from_undirected_edges(4, np.array([[0, 0], [0, 1], [0, 1], [1, 2]]))
    _assert_matches_oracle(g, 0)


def test_pull_queue_bfs_distances_agree():
    g = gnm_graph(300, 900, seed=9)
    r = bfs(g, 7, engine="pull")
    dist, _ = queue_bfs(g, 7)
    np.testing.assert_array_equal(r.dist, dist)
