"""Tests for bfs_tpu.analysis.hlo — the HLO-grade pass (ISSUE 12): every
rule must trip on a fixture program and stay quiet on its near-miss, the
repo's own hot-program registry must compile clean modulo the baseline
with every PROGRAM_SPECS entry fingerprinted, the content-addressed
result cache must hit on an unchanged tree, the CLI must exit non-zero on
each rule fixture and reject scoping, and HLO001 needs its runtime proof:
a deliberately un-donated twin of a shipping step program trips while the
fixed program's executable reports the realized alias.

The repo-wide registry runs carry the ``lint_hlo`` marker so a quick
``-m 'not lint_hlo'`` selection can skip the (cached, but cold-compiled)
jax work; plain tier-1 runs them.
"""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bfs_tpu.analysis import Baseline, default_baseline_path
from bfs_tpu.analysis.hlo import (
    analyze_compiled,
    analyze_hlo,
    compile_program,
    parse_hlo,
)
from bfs_tpu.analysis.ir import Program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
V = 64
INT32_MAX = np.iinfo(np.int32).max


def rules_of(findings):
    return sorted({f.rule for f in findings})


def _mesh(shape=(2,), names=("graph",)):
    n = int(np.prod(shape))
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


# ---------------------------------------------------------------------------
# HLO001 — declared donation must be REALIZED by the executable.
# ---------------------------------------------------------------------------

def _donated_inner():
    return jax.jit(lambda s: s + 1, donate_argnums=0)


def test_hlo001_dropped_donation_trips():
    # Wrapping a donating jit in an OUTER jit silently drops the
    # donation — the exact failure mode the rule exists for.
    inner = _donated_inner()
    outer = jax.jit(lambda s: inner(s))
    prog = Program(
        name="fx.dropped", path="fx.py", fn=outer,
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
        donate={0: "state"},
    )
    fs, _m = analyze_compiled(prog)
    assert rules_of(fs) == ["HLO001"]
    assert "input_output_alias" in fs[0].message


def test_hlo001_near_miss_realized_alias():
    prog = Program(
        name="fx.kept", path="fx.py", fn=_donated_inner(),
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
        donate={0: "state"},
    )
    fs, metrics = analyze_compiled(prog)
    assert fs == []
    # The executable itself reports the alias — the compiler-backed half.
    assert metrics["alias_bytes"] == V * 4


def test_hlo001_runtime_proof_on_shipping_step_program():
    """The acceptance proof: a deliberately un-donated twin of the
    shipping superstep program trips HLO001; the shipped program's
    compiled executable realizes the alias (non-zero alias bytes in
    XLA's own memory analysis)."""
    from bfs_tpu.analysis.ir import PROGRAM_SPECS

    spec = PROGRAM_SPECS["superstep.push_step"]()
    twin = Program(
        name="fx.undonated_step", path=spec.path,
        fn=jax.jit(lambda s: spec.fn(s)),  # outer jit drops donation
        args=spec.args, v_elements=spec.v_elements, donate=spec.donate,
    )
    fs, _m = analyze_compiled(twin)
    assert any(f.rule == "HLO001" for f in fs), rules_of(fs)
    fixed_fs, metrics = analyze_compiled(spec)
    assert not any(f.rule == "HLO001" for f in fixed_fs)
    assert metrics["alias_bytes"] > 0


# ---------------------------------------------------------------------------
# HLO002 — compiler-backed budget + temp-bytes tripwire.
# ---------------------------------------------------------------------------

def test_hlo002_budget_exceeded_trips_and_ample_passes():
    fn = jax.jit(lambda s: s * 2)
    args = (jnp.zeros(4096, jnp.int32),)
    tight = Program(name="fx.tight", path="fx.py", fn=fn, args=args,
                    v_elements=V, budget_bytes=1024)
    ample = Program(name="fx.ample", path="fx.py", fn=fn, args=args,
                    v_elements=V, budget_bytes=1 << 30)
    fs, _m = analyze_compiled(tight)
    assert rules_of(fs) == ["HLO002"]
    assert "buffer assignment" in fs[0].message
    fs, _m = analyze_compiled(ample)
    assert fs == []


def _temps_prog(name):
    # A reduce forces a real temp buffer in XLA's assignment.
    return Program(
        name=name, path="fx.py",
        fn=jax.jit(lambda s: (s * 2).sum() + s),
        args=(jnp.zeros(4096, jnp.int32),), v_elements=V,
        budget_bytes=1 << 30,
    )


def test_hlo002_temp_regression_vs_fingerprint():
    _fs, metrics = analyze_compiled(_temps_prog("fx.probe"))
    temp = metrics["temp_bytes"]
    assert temp > 0
    # >10% over the committed row trips ...
    fs, _m = analyze_compiled(
        _temps_prog("fx.regressed"),
        fingerprint={"temp_bytes": int(temp / 1.5)},
    )
    assert [f.snippet for f in fs] == ["hlo:fx.regressed:regress:temp"]
    # ... within 10% stays quiet (same compile, same bytes).
    fs, _m = analyze_compiled(
        _temps_prog("fx.steady"), fingerprint={"temp_bytes": temp},
    )
    assert fs == []


# ---------------------------------------------------------------------------
# HLO003 — materialized ops inside the while body.
# ---------------------------------------------------------------------------

def _loop_copy_prog(name="fx.loopcopy"):
    @jax.jit
    def loop_copy(x):
        def body(c):
            x, i = c
            y = x.at[i].set(x[(i + 1) % V] + 1)
            # Both the old and the new array stay live -> copy insertion.
            return jnp.where((x.sum() + y.sum()) % 2 == 0, y, x), i + 1

        return jax.lax.while_loop(lambda c: c[1] < 5, body,
                                  (x, jnp.int32(0)))

    return Program(name=name, path="fx.py", fn=loop_copy,
                   args=(jnp.zeros(V, jnp.int32),), v_elements=V)


def test_hlo003_loop_copy_trips():
    fs, metrics = analyze_compiled(_loop_copy_prog())
    assert rules_of(fs) == ["HLO003"]
    assert fs[0].snippet == "hlo:fx.loopcopy:loop:copy"
    assert metrics["loop_materializations"] >= 1


def test_hlo003_near_miss_elementwise_loop():
    @jax.jit
    def clean(x):
        def body(c):
            return c[0] * 2 + 1, c[1] + 1

        return jax.lax.while_loop(lambda c: c[1] < 5, body,
                                  (x, jnp.int32(0)))

    fs, metrics = analyze_compiled(Program(
        name="fx.loopclean", path="fx.py", fn=clean,
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
    ))
    assert fs == []
    assert metrics["loop_materializations"] == 0


def test_hlo003_fusion_count_regression_vs_fingerprint():
    _fs, metrics = analyze_compiled(_loop_copy_prog("fx.probe2"))
    base = dict(metrics)
    # Committed fingerprint claims FEWER fusions than compiled now ->
    # fusion-break tripwire; loop-materialize tripwire likewise.
    fs, _m = analyze_compiled(
        _loop_copy_prog("fx.broke"),
        fingerprint={"fusions": metrics["fusions"] - 1,
                     "loop_materializations": 0,
                     "temp_bytes": metrics["temp_bytes"]},
    )
    snippets = sorted(f.snippet for f in fs if "regress" in f.snippet)
    assert snippets == [
        "hlo:fx.broke:regress:fusions",
        "hlo:fx.broke:regress:loop-materialize",
    ]
    # Matching fingerprint: only the (baselineable) loop:copy finding.
    fs, _m = analyze_compiled(_loop_copy_prog("fx.same"), fingerprint=base)
    assert [f.snippet for f in fs] == ["hlo:fx.same:loop:copy"]


# ---------------------------------------------------------------------------
# HLO004 — compiled collectives vs the declared exchange.
# ---------------------------------------------------------------------------

def _coll_loop_prog(dtype, name, **kwargs):
    mesh = _mesh()

    def outer(x):
        def inner(xb):
            def body(c):
                y, i = c
                merged = jax.lax.psum(y.astype(dtype), "graph")
                return y + merged.astype(y.dtype), i + 1

            return jax.lax.while_loop(
                lambda c: c[1] < 3, body, (xb, jnp.int32(0))
            )[0]

        # check_rep=False: jax-0.4.x has no replication rule for while.
        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P("graph"), check_rep=False)(x)

    kwargs.setdefault("mesh_axes", frozenset({"graph"}))
    kwargs.setdefault("required_axes", frozenset({"graph"}))
    return Program(
        name=name, path="fx.py", fn=jax.jit(outer),
        args=(jnp.zeros(V * 16, jnp.uint32),), v_elements=V, **kwargs,
    )


def test_hlo004_widened_loop_payload_trips():
    fs, metrics = analyze_compiled(_coll_loop_prog(jnp.float32, "fx.fat"))
    assert rules_of(fs) == ["HLO004"]
    assert fs[0].snippet == "hlo:fx.fat:payload:all-reduce:float32"
    assert metrics["loop_collectives"] >= 1


def test_hlo004_near_miss_declared_payload():
    fs, _m = analyze_compiled(_coll_loop_prog(jnp.int32, "fx.okc"))
    assert fs == []


def test_hlo004_collective_in_meshless_program_trips():
    prog = _coll_loop_prog(jnp.int32, "fx.unexp",
                           mesh_axes=None, required_axes=frozenset())
    fs, _m = analyze_compiled(prog)
    assert [f.snippet for f in fs] == ["hlo:fx.unexp:unexpected"]


def test_hlo004_required_exchange_compiled_away_trips():
    mesh = _mesh()

    def no_collective(x):
        return shard_map(lambda xb: xb * 2, mesh=mesh, in_specs=P("graph"),
                         out_specs=P("graph"))(x)

    prog = Program(
        name="fx.nocoll", path="fx.py", fn=jax.jit(no_collective),
        args=(jnp.zeros(V * 2, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )
    fs, _m = analyze_compiled(prog)
    assert [f.snippet for f in fs] == ["hlo:fx.nocoll:missing-collective"]


def test_hlo004_loop_collective_count_change_trips_both_ways():
    for claimed, word in ((2, "hoisted"), (0, "duplicated")):
        fs, _m = analyze_compiled(
            _coll_loop_prog(jnp.int32, "fx.moved"),
            fingerprint={"loop_collectives": claimed},
        )
        assert [f.snippet for f in fs] == ["hlo:fx.moved:regress:collectives"]
        assert word in fs[0].message


# ---------------------------------------------------------------------------
# HLO005 — opaque escapes.
# ---------------------------------------------------------------------------

def test_hlo005_custom_call_trips():
    # linalg lowers to a lapack custom-call on the CPU backend.
    prog = Program(
        name="fx.chol", path="fx.py",
        fn=jax.jit(lambda a: jnp.linalg.cholesky(a)),
        args=(jnp.eye(8, dtype=jnp.float32) * 4,), v_elements=4,
    )
    fs, _m = analyze_compiled(prog)
    assert [f.snippet for f in fs] == ["hlo:fx.chol:escape:custom-call"]


def test_hlo005_near_miss_pure_xla():
    prog = Program(
        name="fx.pure", path="fx.py",
        fn=jax.jit(lambda a: (a * 2).sum()),
        args=(jnp.zeros(64, jnp.float32),), v_elements=4,
    )
    fs, _m = analyze_compiled(prog)
    assert fs == []


# ---------------------------------------------------------------------------
# HLO000 — uncompilable programs fail loudly.
# ---------------------------------------------------------------------------

def test_hlo000_uncompilable_program_is_an_error():
    def broken(x):
        raise TypeError("deliberately uncompilable")

    prog = Program(name="fx.broken", path="fx.py", fn=broken,
                   args=(jnp.zeros(4, jnp.int32),), v_elements=V)
    fs, metrics = analyze_compiled(prog)
    assert rules_of(fs) == ["HLO000"]
    assert metrics == {}


# ---------------------------------------------------------------------------
# The HLO text parser itself.
# ---------------------------------------------------------------------------

def test_parse_hlo_walks_while_bodies_and_aliases():
    fn = jax.jit(lambda s: s + 1, donate_argnums=0)
    module, _mem = compile_program(Program(
        name="fx.p", path="fx.py", fn=fn,
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
    ))
    assert module.aliased_params == frozenset({0})
    assert module.entry
    # A while program's loop computations are found transitively.
    @jax.jit
    def loopy(x):
        def body(c):
            return c[0] + jnp.where(c[0] > 0, 1, 2), c[1] + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body,
                                  (x, jnp.int32(0)))

    module, _mem = compile_program(Program(
        name="fx.w", path="fx.py", fn=loopy,
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
    ))
    loop_comps = module.loop_computations()
    assert loop_comps, "while body/condition not discovered"
    assert all(name in module.computations for name in loop_comps)


def test_shape_bytes_tuple_and_scalar():
    from bfs_tpu.analysis.hlo import shape_bytes

    assert shape_bytes("s32[64]{0}") == 256
    assert shape_bytes("u32[2,10]{1,0}") == 80
    assert shape_bytes("pred[]") == 1
    assert shape_bytes("(s32[4]{0}, u32[8]{0})") == 16 + 32


# ---------------------------------------------------------------------------
# The repo registry: self-lint + fingerprint coverage + cache.
# ---------------------------------------------------------------------------

@pytest.mark.lint_hlo
def test_repo_hlo_self_lint_clean_modulo_baseline():
    """Every declared hot program COMPILES and passes the HLO rules (the
    tier-1 'what XLA emits is clean' gate — the compiled twin of the IR
    self-lint)."""
    findings, meta = analyze_hlo(use_cache=True)
    # Hot-coverage pin: the registry keeps >= 25 programs and every one
    # is compiled (or explicitly skipped), never silently dropped.
    assert len(meta["programs"]) + len(meta["skipped"]) >= 28, meta
    # The committed fingerprint file must match the container env and
    # cover every compiled program — deleting a program's HLO coverage
    # fails tier-1 here.
    assert meta["fingerprint_status"] == "match", meta["fingerprint_status"]
    assert meta["unfingerprinted"] == [], meta["unfingerprinted"]
    baseline = Baseline.load(default_baseline_path())
    fresh = [f for f in findings if not baseline.accepts(f)]
    assert fresh == [], "\n".join(f.render() for f in fresh)
    # Donation realization must stay proven on every declared carry: the
    # CPU backend realizes all four step-program aliases today, and a
    # jax upgrade that stops realizing them must fail here loudly.
    assert not any(f.rule == "HLO001" for f in findings)


def _small_registry():
    return {
        "fx.small_a": lambda: Program(
            name="fx.small_a", path="fx.py",
            fn=jax.jit(lambda s: s * 2 + 1),
            args=(jnp.zeros(V, jnp.int32),), v_elements=V,
        ),
        "fx.small_b": lambda: _loop_copy_prog("fx.small_b"),
    }


@pytest.mark.lint_hlo
def test_hlo_result_cache_hits_on_unchanged_tree(tmp_path, monkeypatch):
    from bfs_tpu.analysis import hlo as hlo_mod

    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", _small_registry())
    f1, m1 = analyze_hlo(use_cache=True, cache_dir=str(tmp_path))
    assert m1["cache"] == "miss"
    f2, m2 = analyze_hlo(use_cache=True, cache_dir=str(tmp_path))
    assert m2["cache"] == "hit"
    assert [f.fingerprint() for f in f2] == [f.fingerprint() for f in f1]
    assert m2["fingerprints"] == m1["fingerprints"]
    assert any(name.startswith("hlo_") for name in os.listdir(tmp_path))


def test_hlo_skip_records_program():
    from bfs_tpu.analysis.ir import SkipProgram

    def skipper():
        raise SkipProgram("no mesh here")

    findings, meta = analyze_hlo({"fx.skipped": skipper})
    assert findings == []
    assert meta["skipped"] == {"fx.skipped": "no mesh here"}
    assert meta["cache"] == "off"  # custom specs are never cached


def test_hlo_foreign_fingerprint_env_disables_regression(tmp_path):
    """A fingerprint file generated on another backend/jax must not
    produce regression findings — its counts are not comparable."""
    from bfs_tpu.analysis.hlo import current_env, load_fingerprints

    fp = tmp_path / "fp.json"
    fp.write_text(json.dumps({
        "env": {"backend": "tpu", "devices": 4, "jax": "9.9.9"},
        "programs": {"fx.small_b": {"temp_bytes": 1, "fusions": 0,
                                    "loop_materializations": 0}},
    }))
    status, programs = load_fingerprints(str(fp))
    assert status == "foreign" and "fx.small_b" in programs
    findings, meta = analyze_hlo(
        _small_registry(), fingerprints_path=str(fp)
    )
    assert meta["fingerprint_status"] == "foreign"
    assert not any("regress" in f.snippet for f in findings)
    # Same rows under the CURRENT env: the regressions fire.
    fp2 = tmp_path / "fp2.json"
    fp2.write_text(json.dumps({
        "env": current_env(),
        "programs": {"fx.small_b": {"temp_bytes": 1, "fusions": 0,
                                    "loop_materializations": 0}},
    }))
    findings, meta = analyze_hlo(
        _small_registry(), fingerprints_path=str(fp2)
    )
    assert meta["fingerprint_status"] == "match"
    assert any("regress" in f.snippet for f in findings)


# ---------------------------------------------------------------------------
# CLI: the --hlo path.
# ---------------------------------------------------------------------------

def _fixture_specs():
    mesh_ok = len(jax.devices()) >= 2
    inner = _donated_inner()
    outer = jax.jit(lambda s: inner(s))
    specs = {
        "HLO001": lambda: Program(
            name="fx.dropped", path="fx.py", fn=outer,
            args=(jnp.zeros(V, jnp.int32),), v_elements=V,
            donate={0: "state"},
        ),
        "HLO002": lambda: Program(
            name="fx.tight", path="fx.py", fn=jax.jit(lambda s: s * 2),
            args=(jnp.zeros(4096, jnp.int32),), v_elements=V,
            budget_bytes=1024,
        ),
        "HLO003": lambda: _loop_copy_prog(),
        "HLO005": lambda: Program(
            name="fx.chol", path="fx.py",
            fn=jax.jit(lambda a: jnp.linalg.cholesky(a)),
            args=(jnp.eye(8, dtype=jnp.float32) * 4,), v_elements=4,
        ),
    }
    if mesh_ok:
        specs["HLO004"] = lambda: _coll_loop_prog(jnp.float32, "fx.fat")
    return specs


@pytest.mark.parametrize("rule", ["HLO001", "HLO002", "HLO003", "HLO004",
                                  "HLO005"])
def test_cli_exits_nonzero_on_rule_fixture(rule, monkeypatch, capsys):
    specs = _fixture_specs()
    if rule not in specs:
        pytest.skip("needs 2 devices")
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", {rule: specs[rule]})
    rc = cli.main(["--hlo", "--no-cache", "--no-baseline"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert rule in out.out


def test_cli_hlo_subcommand_and_baseline_accept(monkeypatch, tmp_path,
                                                capsys):
    """`python -m bfs_tpu.analysis hlo` == `--hlo`; a justified baseline
    entry turns the same fixture run green."""
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    specs = _fixture_specs()
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS",
                        {"HLO003": specs["HLO003"]})
    [finding], _m = analyze_compiled(specs["HLO003"]())
    bl = tmp_path / "baseline.txt"
    bl.write_text(
        f"{finding.rule}  {finding.fingerprint()}  fixture: accepted\n"
    )
    rc = cli.main(["hlo", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_hlo_rejects_scoping_flags(capsys):
    from bfs_tpu.analysis import __main__ as cli

    for argv in (["--hlo", "--changed"], ["--hlo", "some/file.py"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, (argv, out.out, out.err)
        assert "cannot be scoped" in out.err
    rc = cli.main(["--ir", "--hlo"])
    out = capsys.readouterr()
    assert rc == 2
    assert "one at a time" in out.err
    for argv in (["--update-fingerprints"], ["--snapshot", "x.json"],
                 ["--ir", "--update-fingerprints"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, argv
        assert "--hlo" in out.err


def test_cli_stale_hlo_entry_fails_default_surface(monkeypatch, tmp_path,
                                                   capsys):
    """A stale `hlo:` fingerprint fails a default-surface --hlo run
    exactly like `ir:` ones (ISSUE 12 satellite) — and entries from the
    OTHER families are not this pass's business."""
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    clean = {"fx.clean": lambda: Program(
        name="fx.clean", path="fx.py", fn=jax.jit(lambda s: s * 2),
        args=(jnp.zeros(V, jnp.int32),), v_elements=V,
    )}
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", clean)
    bl = tmp_path / "baseline.txt"
    bl.write_text("HLO003  deadbeef0000  a dead hlo entry\n")
    rc = cli.main(["--hlo", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "STALE" in out.err
    # An AST-family entry in the same file is NOT stale for this pass.
    bl.write_text("TRC001  deadbeef0000  an ast entry\n")
    rc = cli.main(["--hlo", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_hlo_write_baseline_prints_never_clobbers(monkeypatch,
                                                      tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    specs = _fixture_specs()
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS",
                        {"HLO003": specs["HLO003"]})
    bl = tmp_path / "baseline.txt"
    bl.write_text("TRC001  cafecafe0000  keep me\n")
    rc = cli.main(["--hlo", "--no-cache", "--write-baseline",
                   "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0
    assert "HLO003" in out.out  # candidate line printed
    assert "HLO section" in out.err
    assert bl.read_text() == "TRC001  cafecafe0000  keep me\n"  # untouched


def test_cli_hlo_snapshot_writes_metrics(monkeypatch, tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", _small_registry())
    snap = tmp_path / "snap.json"
    cli.main(["--hlo", "--no-cache", "--no-baseline",
              "--snapshot", str(snap)])
    capsys.readouterr()
    doc = json.loads(snap.read_text())
    assert set(doc["programs"]) == {"fx.small_a", "fx.small_b"}
    assert doc["env"]["backend"] == jax.default_backend()
    assert "temp_bytes" in doc["programs"]["fx.small_a"]


def test_hlo_finding_fingerprint_is_line_drift_proof():
    [f], _m = analyze_compiled(_loop_copy_prog())
    assert f.snippet == "hlo:fx.loopcopy:loop:copy"
    assert f.line == 0


# ---------------------------------------------------------------------------
# tools/hlo_diff.py — the compiled-artifact ledger_compare twin.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def hlo_diff():
    spec = importlib.util.spec_from_file_location(
        "hlo_diff", os.path.join(REPO, "tools", "hlo_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _snap(path, programs, env=None):
    path.write_text(json.dumps(
        {"env": env or {}, "programs": programs}
    ))
    return str(path)


_BASE_ROW = {"temp_bytes": 1000, "fusions": 10, "loop_collectives": 2,
             "loop_materializations": 1}


def test_hlo_diff_detects_synthetic_fusion_break(hlo_diff, tmp_path,
                                                 capsys):
    old = _snap(tmp_path / "old.json", {"relay.fused": dict(_BASE_ROW)})
    broke = dict(_BASE_ROW, fusions=12, temp_bytes=1300)
    new = _snap(tmp_path / "new.json", {"relay.fused": broke})
    rc = hlo_diff.main([old, new])
    out = capsys.readouterr().out
    assert rc == 1
    assert "| relay.fused |" in out  # markdown delta table
    assert "fusion break" in out
    assert "+30%" in out


def test_hlo_diff_clean_and_regression_axes(hlo_diff, tmp_path, capsys):
    old = _snap(tmp_path / "o.json", {"p": dict(_BASE_ROW)})
    assert hlo_diff.main([old, old]) == 0
    capsys.readouterr()
    # A hoisted loop collective is a regression even though the count
    # went DOWN — the wire shape changed.
    hoisted = _snap(tmp_path / "h.json",
                    {"p": dict(_BASE_ROW, loop_collectives=1)})
    assert hlo_diff.main([old, hoisted]) == 1
    assert "hoisted" in capsys.readouterr().out
    # A removed program is a coverage regression.
    gone = _snap(tmp_path / "g.json", {})
    assert hlo_diff.main([old, gone]) == 1
    assert "disappeared" in capsys.readouterr().out
    # A new program is informational only.
    grown = _snap(tmp_path / "n.json",
                  {"p": dict(_BASE_ROW), "q": dict(_BASE_ROW)})
    assert hlo_diff.main([old, grown]) == 0


def test_hlo_diff_rejects_foreign_environments(hlo_diff, tmp_path, capsys):
    old = _snap(tmp_path / "a.json", {"p": dict(_BASE_ROW)},
                env={"backend": "cpu", "devices": 8, "jax": "0.4.37"})
    new = _snap(tmp_path / "b.json", {"p": dict(_BASE_ROW)},
                env={"backend": "tpu", "devices": 4, "jax": "0.4.37"})
    assert hlo_diff.main([old, new]) == 2
    assert "not comparable" in capsys.readouterr().err


def test_hlo_diff_reads_committed_fingerprints(hlo_diff, capsys):
    """The committed fingerprint file is itself a valid diff input — the
    TPU-window before/after spelling is one command against it."""
    path = os.path.join(REPO, "bfs_tpu", "analysis",
                        "hlo_fingerprints.json")
    assert hlo_diff.main([path, path]) == 0
    assert "no regressions" in capsys.readouterr().out


def test_cli_update_fingerprints_refuses_on_compile_failure(
        monkeypatch, tmp_path, capsys):
    """--update-fingerprints must not silently drop a program whose
    compile failed — the row would vanish from the committed file with
    exit 0 and only resurface as a set-inequality test failure later."""
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod

    def broken():
        raise TypeError("deliberately uncompilable spec")

    out_path = tmp_path / "fp.json"
    monkeypatch.setattr(hlo_mod, "default_fingerprints_path",
                        lambda: str(out_path))
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", {"fx.broken": broken})
    rc = cli.main(["--hlo", "--no-cache", "--update-fingerprints"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "refusing" in out.err and "HLO000" in out.out
    assert not out_path.exists()
    # With a compiling registry the same spelling writes the file.
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS", _small_registry())
    rc = cli.main(["--hlo", "--no-cache", "--update-fingerprints"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    doc = json.loads(out_path.read_text())
    assert set(doc["programs"]) == {"fx.small_a", "fx.small_b"}


def test_hlo002_budget_does_not_double_count_realized_alias():
    """A donated carry appears in BOTH argument and output bytes but
    occupies one buffer — the budget proof must subtract the alias or a
    fitting donated program false-trips at ~2x its real footprint."""
    n = 4096
    prog = Program(
        name="fx.aliased", path="fx.py",
        fn=jax.jit(lambda s: s + 1, donate_argnums=0),
        args=(jnp.zeros(n, jnp.int32),), v_elements=V,
        donate={0: "state"},
        budget_bytes=int(n * 4 * 1.5),  # fits once, not twice
    )
    fs, metrics = analyze_compiled(prog)
    assert metrics["alias_bytes"] == n * 4
    assert fs == [], [f.render() for f in fs]


def test_cli_update_fingerprints_refuses_on_skipped_program(
        monkeypatch, tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import hlo as hlo_mod
    from bfs_tpu.analysis.ir import SkipProgram

    def skipper():
        raise SkipProgram("too few devices")

    out_path = tmp_path / "fp.json"
    monkeypatch.setattr(hlo_mod, "default_fingerprints_path",
                        lambda: str(out_path))
    monkeypatch.setattr(hlo_mod, "PROGRAM_SPECS",
                        {**_small_registry(), "fx.skipped": skipper})
    rc = cli.main(["--hlo", "--no-cache", "--update-fingerprints"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "skipped" in out.err and "fx.skipped" in out.err
    assert not out_path.exists()
