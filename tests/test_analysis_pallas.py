"""Tests for bfs_tpu.analysis.pallas — the kernel-grade pass (ISSUE 13):
every PAL rule must trip on a fixture kernel and stay quiet on its
near-miss, the repo's own kernel registry must run clean modulo the
baseline with every ``pl.pallas_call`` site covered (the set-equality
pin), the content-addressed result cache must hit on an unchanged tree,
the CLI must exit non-zero on each rule fixture and reject scoping, the
``--all`` composite surface must merge every pass under one exit code,
and PAL005 needs its runtime proof: a deliberately broken twin of a
shipping kernel trips the parity oracle while the shipped registry's
twins all match bit-identically.

The repo-wide registry runs carry the ``lint_pallas`` marker so a quick
``-m 'not lint_pallas'`` selection can skip the (cached, but cold ~20 s)
interpret-mode work; plain tier-1 runs them.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bfs_tpu.analysis import Baseline, default_baseline_path
from bfs_tpu.analysis import pallas as pal_mod
from bfs_tpu.analysis.pallas import (
    KERNEL_SPECS,
    KernelCase,
    KernelSpec,
    Window,
    analyze_kernel,
    analyze_pallas,
    capture_pallas_calls,
    discover_pallas_sites,
    registered_sites,
    registry_findings,
    tree_bit_identical,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted({f.rule for f in findings})


def _spec(name, build):
    return KernelSpec(name=name, path="fx.py", sites=(), build=build)


def _pallas_double(
    rows=16,
    lanes=128,
    block=(8, 128),
    grid=None,
    in_map=None,
    out_map=None,
    scratch=None,
):
    """Run a trivial doubling kernel through pl.pallas_call with the
    given blocking — the knob set every fixture below turns."""
    from jax.experimental import pallas as pl

    x = jnp.arange(rows * lanes, dtype=jnp.uint32).reshape(rows, lanes)
    grid = grid if grid is not None else rows // block[0]
    in_map = in_map or (lambda i: (i, 0))
    out_map = out_map or in_map

    def kernel(x_ref, o_ref, *_scratch):
        o_ref[...] = x_ref[...] * 2

    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(block, in_map)],
        out_specs=pl.BlockSpec(block, out_map),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), jnp.uint32),
        scratch_shapes=list(scratch or ()),
        interpret=True,
    )(x)


# ---------------------------------------------------------------------------
# The capture spy itself.
# ---------------------------------------------------------------------------

def test_capture_records_real_call_parameters():
    from jax.experimental.pallas import tpu as pltpu

    def run():
        return _pallas_double(
            scratch=[pltpu.VMEM((2, 8, 128), jnp.uint32),
                     pltpu.SemaphoreType.DMA((2,))],
        )

    result, records = capture_pallas_calls(run)
    assert len(records) == 1
    rec = records[0]
    assert rec.grid == (2,)
    assert rec.in_specs[0].block_shape == (8, 128)
    assert rec.out_specs[0].block_shape == (8, 128)
    # semaphores are not VMEM; the 2x8x128 u32 buffer is.
    assert rec.scratch_bytes == 2 * 8 * 128 * 4
    assert rec.interpret
    assert int(np.asarray(result)[1, 1]) == 2 * 129


def test_tree_bit_identical_catches_shape_dtype_value():
    a = {"x": jnp.zeros(4, jnp.uint32), "y": jnp.int32(3)}
    ok, _ = tree_bit_identical(a, {"x": jnp.zeros(4, jnp.uint32),
                                   "y": jnp.int32(3)})
    assert ok
    ok, d = tree_bit_identical(a, {"x": jnp.zeros(4, jnp.int32),
                                   "y": jnp.int32(3)})
    assert not ok and "dtype" in d
    ok, d = tree_bit_identical(a, {"x": jnp.zeros(5, jnp.uint32),
                                   "y": jnp.int32(3)})
    assert not ok and "shape" in d
    ok, d = tree_bit_identical(a, {"x": jnp.ones(4, jnp.uint32),
                                   "y": jnp.int32(3)})
    assert not ok and "differ" in d


# ---------------------------------------------------------------------------
# PAL001 — VMEM residency proof.
# ---------------------------------------------------------------------------

def _scratch_hog_case():
    from jax.experimental.pallas import tpu as pltpu

    # 32 MB of declared VMEM scratch blows the 16 MB default budget.
    return KernelCase(run=lambda: _pallas_double(
        scratch=[pltpu.VMEM((1 << 23,), jnp.uint32)]
    ))


def test_pal001_vmem_over_budget_trips_and_small_passes(monkeypatch):
    fs = analyze_kernel(_spec("fx.hog", _scratch_hog_case))
    assert rules_of(fs) == ["PAL001"]
    assert "scratch" in fs[0].message and "BFS_TPU_PAL_VMEM_MB" in fs[0].message
    # A raised budget accepts the same kernel.
    monkeypatch.setenv("BFS_TPU_PAL_VMEM_MB", "64")
    assert analyze_kernel(_spec("fx.hog2", _scratch_hog_case)) == []
    monkeypatch.delenv("BFS_TPU_PAL_VMEM_MB")
    # The clean fixture is far under budget.
    fs = analyze_kernel(_spec(
        "fx.small", lambda: KernelCase(run=lambda: _pallas_double())
    ))
    assert fs == []


# ---------------------------------------------------------------------------
# PAL002 — (8, 128) tiling + MXU readiness.
# ---------------------------------------------------------------------------

def test_pal002_misaligned_block_trips_aligned_passes():
    fs = analyze_kernel(_spec("fx.misaligned", lambda: KernelCase(
        run=lambda: _pallas_double(rows=4, lanes=64, block=(4, 64),
                                   grid=1, in_map=lambda i: (0, 0)),
    )))
    # in and out blocks both misaligned (sublane 4 % 8, lane 64 % 128).
    assert rules_of(fs) == ["PAL002"]
    assert len(fs) == 2
    assert "4x64" in fs[0].snippet
    assert analyze_kernel(_spec(
        "fx.aligned", lambda: KernelCase(run=lambda: _pallas_double())
    )) == []


def test_pal002_mxu_contract():
    # (8, 128) satisfies the VPU tiling but NOT the declared-MXU 128x128.
    fs = analyze_kernel(_spec("fx.mxu", lambda: KernelCase(
        run=lambda: _pallas_double(), mxu=True,
    )))
    assert rules_of(fs) == ["PAL002"]
    assert all("mxu" in f.snippet for f in fs)
    fs = analyze_kernel(_spec("fx.mxu_ok", lambda: KernelCase(
        run=lambda: _pallas_double(rows=256, block=(128, 128), grid=2),
        mxu=True,
    )))
    assert fs == []


# ---------------------------------------------------------------------------
# PAL003 — grid write-aliasing.
# ---------------------------------------------------------------------------

def test_pal003_output_race_trips_accumulate_declared_passes():
    def racing(accumulates):
        # Two grid steps both map output block (0, 0) of an 8-row out.
        return KernelCase(
            run=lambda: _pallas_double(
                rows=8, grid=2,
                in_map=lambda i: (0, 0), out_map=lambda i: (0, 0),
            ),
            accumulates=accumulates,
        )

    fs = analyze_kernel(_spec("fx.race", lambda: racing(False)))
    assert rules_of(fs) == ["PAL003"]
    assert "race" in fs[0].snippet and "data race" in fs[0].message
    assert analyze_kernel(_spec("fx.accum", lambda: racing(True))) == []


def test_pal003_shifted_output_map_trips_overrun_and_uncovered():
    """An off-by-one OUTPUT index map writes a phantom block past the
    array and leaves block 0 unwritten: the phantom must not count as
    coverage (review finding) — PAL003 reports the garbage block and
    PAL004 the out-of-bounds write."""
    fs = analyze_kernel(_spec("fx.shifted", lambda: KernelCase(
        run=lambda: _pallas_double(
            rows=16, grid=2, out_map=lambda i: (i + 1, 0),
        ),
    )))
    assert rules_of(fs) == ["PAL003", "PAL004"], [f.snippet for f in fs]
    assert any("uncovered" in f.snippet for f in fs)
    assert any("block-overrun" in f.snippet for f in fs)


def test_pal003_uncovered_output_blocks_trip():
    # Grid of 1 writes only the first of two output blocks; the input
    # tail is equally dropped — both halves of the bug are reported.
    fs = analyze_kernel(_spec("fx.uncovered", lambda: KernelCase(
        run=lambda: _pallas_double(rows=16, grid=1),
    )))
    assert rules_of(fs) == ["PAL003", "PAL004"]
    assert any("uncovered" in f.snippet for f in fs)
    assert any("unread-blocks" in f.snippet for f in fs)


# ---------------------------------------------------------------------------
# PAL004 — dynamic-slice bounds.
# ---------------------------------------------------------------------------

def test_pal004_interior_unread_input_block_trips():
    """Coverage is an exact block-set count, not a high-watermark
    (review finding): an index map that reads block 1 twice and skips
    block 2 reaches the array end yet misses interior rows."""
    fs = analyze_kernel(_spec("fx.hole", lambda: KernelCase(
        run=lambda: _pallas_double(
            rows=32, grid=4,
            in_map=lambda i: (i - (i == 2), 0),
            out_map=lambda i: (i, 0),
        ),
    )))
    assert rules_of(fs) == ["PAL004"], [f.snippet for f in fs]
    assert "unread-blocks" in fs[0].snippet
    assert "3 of 4" in fs[0].message


def test_tree_bit_identical_is_bitwise_not_value_equality():
    """-0.0 == 0.0 by value but not by bits; NaN != NaN by value but a
    bit-identical NaN is parity (review finding) — the oracle compares
    raw bytes."""
    ok, d = tree_bit_identical(jnp.float32(-0.0), jnp.float32(0.0))
    assert not ok and "bit-wise" in d
    nan = jnp.asarray([np.nan, 1.0], jnp.float32)
    ok, _ = tree_bit_identical(nan, jnp.asarray([np.nan, 1.0], jnp.float32))
    assert ok


def test_pal004_manual_window_overrun_trips_fitting_passes():
    def with_window(limit):
        return KernelCase(
            run=lambda: _pallas_double(),
            windows=[Window("fx:stage0", 4, 8, limit)],
        )

    fs = analyze_kernel(_spec("fx.window", lambda: with_window(10)))
    assert rules_of(fs) == ["PAL004"]
    assert "window" in fs[0].snippet and "[4, 12)" in fs[0].message
    assert analyze_kernel(_spec("fx.winok", lambda: with_window(12))) == []


def test_pal004_benes_window_helper_catches_corrupt_stage_table():
    """The windows helper mirrors the kernels' pl.ds arithmetic: a stage
    offset pointing past the prepared mask array must produce an
    out-of-bounds window."""
    from bfs_tpu.analysis.pallas import benes_word_windows
    from bfs_tpu.graph.relay import StageSpec

    # One local_tm pass, 2 tiles of 8 rows, one full stage: 16 rows of
    # masks needed; claim only 12 exist.
    st = StageSpec(d=1, offset=0, nwords=8 * 128, compact=False,
                   lo=0, hi=8 * 128)
    ps = (("local_tm", 8, 8, (st,)),)
    windows = benes_word_windows(ps, [12], 16 * 32 * 128)
    assert any(w.start + w.size > w.limit for w in windows)
    ok = benes_word_windows(ps, [16], 16 * 32 * 128)
    assert all(w.start + w.size <= w.limit for w in ok)


# ---------------------------------------------------------------------------
# PAL005 — the interpret-vs-XLA parity oracle.
# ---------------------------------------------------------------------------

def test_pal005_broken_twin_trips_matching_passes():
    def broken():
        return KernelCase(
            run=lambda: _pallas_double(),
            twin=lambda: _pallas_double() + jnp.uint32(1),
        )

    fs = analyze_kernel(_spec("fx.skew", broken))
    assert rules_of(fs) == ["PAL005"]
    assert "bit-identical" in fs[0].message

    def matching():
        return KernelCase(
            run=lambda: _pallas_double(),
            twin=lambda: _pallas_double(),
        )

    assert analyze_kernel(_spec("fx.match", matching)) == []


@pytest.mark.lint_pallas
def test_pal005_runtime_proof_on_shipping_kernel():
    """The acceptance proof: a deliberately broken twin of the SHIPPING
    packed-update kernel trips the parity oracle; the shipped spec's own
    twin matches bit-identically (asserted for every registered kernel
    by the self-lint below)."""
    real = KERNEL_SPECS["update.packed_words"]()

    def broken_build():
        case = real.build()
        orig_twin = case.twin

        def twin():
            r = orig_twin()
            return r._replace(packed=r.packed ^ jnp.uint32(1))

        return KernelCase(run=case.run, twin=twin)

    fs = analyze_kernel(KernelSpec(
        name="fx.broken_update_twin", path=real.path, sites=(),
        build=broken_build,
    ))
    assert any(f.rule == "PAL005" for f in fs), rules_of(fs)
    # The shipped spec's twin matches (its only finding is the
    # baselined PAL002 tile note — never a parity break).
    assert not any(f.rule == "PAL005" for f in analyze_kernel(real))


def test_pal005_can_never_be_baselined(monkeypatch, tmp_path, capsys):
    """The documented contract, ENFORCED (review finding): a justified
    baseline entry for a PAL005 parity break is ignored — the run stays
    red and the dead entry reports stale."""
    from bfs_tpu.analysis import __main__ as cli

    spec_build = _fixture_specs()["PAL005"]
    monkeypatch.setattr(pal_mod, "KERNEL_SPECS", {"PAL005": spec_build})
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    [finding] = [f for f in analyze_kernel(spec_build())
                 if f.rule == "PAL005"]
    bl = tmp_path / "baseline.txt"
    bl.write_text(
        f"PAL005  {finding.fingerprint()}  trying to silence parity\n"
    )
    rc = cli.main(["--pallas", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "PAL005" in out.out  # still reported, not accepted


def test_pal000_undecodable_grid_spec_call_fails_loudly():
    """A kernel passing grid_spec= (the PrefetchScalarGridSpec shape)
    gives the spy empty spec lists — every static rule would pass
    vacuously, so the capture itself must be a PAL000 (review
    finding)."""
    from jax.experimental import pallas as pl

    def run():
        bs = pl.BlockSpec((8, 128), lambda i: (i, 0))
        gs = pl.GridSpec(grid=(2,), in_specs=[bs], out_specs=bs)

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2

        return pl.pallas_call(
            kernel, grid_spec=gs,
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.uint32),
            interpret=True,
        )(jnp.ones((16, 128), jnp.uint32))

    fs = analyze_kernel(_spec("fx.gridspec", lambda: KernelCase(run=run)))
    assert any(f.snippet == "pal:fx.gridspec:undecoded:kernel"
               for f in fs), [f.snippet for f in fs]


def test_pal000_no_pallas_call_and_builder_failure():
    fs = analyze_kernel(_spec(
        "fx.nocall", lambda: KernelCase(run=lambda: jnp.zeros(4))
    ))
    assert [f.snippet for f in fs] == ["pal:fx.nocall:no-pallas-call"]

    def boom():
        raise TypeError("deliberately broken case")

    fs = analyze_kernel(_spec("fx.boom", boom))
    assert [f.snippet for f in fs] == ["pal:fx.boom:build"]


# ---------------------------------------------------------------------------
# The registry <-> pallas_call-site set-equality pin.
# ---------------------------------------------------------------------------

def test_registry_covers_every_pallas_call_site():
    """Tier-1 pin: every pl.pallas_call site in bfs_tpu/ has a
    KERNEL_SPECS entry and every spec site exists — deleting a spec OR
    adding an unregistered kernel fails here."""
    discovered = discover_pallas_sites(REPO)
    assert discovered == registered_sites(), (
        sorted(discovered), sorted(registered_sites())
    )
    # The six shipped sites, by name — a rename must update the specs.
    assert {s.split("::")[1] for s in discovered} == {
        "_run_local_tile_major", "_run_pass", "_run_elem_pass",
        "_class_tournament_call", "apply_relay_candidates_packed_pallas",
        "expand_frontier_mxu",
    }
    assert registry_findings(KERNEL_SPECS, REPO) == []


def test_registry_findings_flag_both_directions():
    pruned = dict(KERNEL_SPECS)
    del pruned["rowmin.tournament"]
    fs = registry_findings(pruned, REPO)
    assert any("unregistered" in f.snippet
               and "_class_tournament_call" in f.snippet for f in fs)

    def ghost_build():  # never called — coverage is read statically
        raise AssertionError

    ghost_build.sites = ("bfs_tpu/ops/relay_pallas.py::_gone_kernel",)
    fs = registry_findings({**KERNEL_SPECS, "fx.ghost": ghost_build}, REPO)
    assert any("missing" in f.snippet and "_gone_kernel" in f.snippet
               for f in fs)


# ---------------------------------------------------------------------------
# The repo registry: self-lint + cache.
# ---------------------------------------------------------------------------

@pytest.mark.lint_pallas
def test_repo_pallas_self_lint_clean_modulo_baseline():
    """Every shipped kernel runs, every pallas_call site is covered, and
    the findings are clean modulo the committed baseline.  PAL005 parity
    is asserted bit-identical for EVERY registered kernel: a parity
    break can never be baselined into silence here."""
    findings, meta = analyze_pallas(use_cache=True)
    assert len(meta["kernels"]) + len(meta["skipped"]) >= 6, meta
    assert meta["skipped"] == {}, meta["skipped"]  # native router in-image
    baseline = Baseline.load(default_baseline_path())
    fresh = [f for f in findings if not baseline.accepts(f)]
    assert fresh == [], "\n".join(f.render() for f in fresh)
    assert not any(f.rule == "PAL005" for f in findings)
    assert not any(f.rule == "PAL000" for f in findings)
    # Every kernel reports its VMEM proof input (the meta the docs cite).
    assert set(meta["vmem_bytes"]) == set(meta["kernels"])


def _small_registry():
    def a():
        return _spec("fx.small_a", lambda: KernelCase(
            run=lambda: _pallas_double()
        ))

    def b():
        return _spec("fx.small_b", lambda: KernelCase(
            run=lambda: _pallas_double(rows=8, grid=1)
        ))

    a.sites = ()
    b.sites = ()
    return {"fx.small_a": a, "fx.small_b": b}


def test_pallas_result_cache_hits_on_unchanged_tree(tmp_path, monkeypatch):
    monkeypatch.setattr(pal_mod, "KERNEL_SPECS", _small_registry())
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    f1, m1 = analyze_pallas(use_cache=True, cache_dir=str(tmp_path))
    assert m1["cache"] == "miss"
    f2, m2 = analyze_pallas(use_cache=True, cache_dir=str(tmp_path))
    assert m2["cache"] == "hit"
    assert [f.fingerprint() for f in f2] == [f.fingerprint() for f in f1]
    assert m2["vmem_bytes"] == m1["vmem_bytes"]
    assert any(name.startswith("pal_") for name in os.listdir(tmp_path))


def test_pallas_skip_records_kernel():
    from bfs_tpu.analysis.ir import SkipProgram

    def skipper():
        raise SkipProgram("no native router")

    findings, meta = analyze_pallas({"fx.skipped": skipper})
    assert findings == []
    assert meta["skipped"] == {"fx.skipped": "no native router"}
    assert meta["cache"] == "off"  # custom specs are never cached


# ---------------------------------------------------------------------------
# CLI: the --pallas path.
# ---------------------------------------------------------------------------

def _fixture_specs():
    return {
        "PAL001": lambda: _spec("fx.hog", _scratch_hog_case),
        "PAL002": lambda: _spec("fx.misaligned", lambda: KernelCase(
            run=lambda: _pallas_double(rows=4, lanes=64, block=(4, 64),
                                       grid=1, in_map=lambda i: (0, 0)),
        )),
        "PAL003": lambda: _spec("fx.race", lambda: KernelCase(
            run=lambda: _pallas_double(
                rows=8, grid=2,
                in_map=lambda i: (0, 0), out_map=lambda i: (0, 0),
            ),
        )),
        "PAL004": lambda: _spec("fx.window", lambda: KernelCase(
            run=lambda: _pallas_double(),
            windows=[Window("fx:stage0", 4, 8, 10)],
        )),
        "PAL005": lambda: _spec("fx.skew", lambda: KernelCase(
            run=lambda: _pallas_double(),
            twin=lambda: _pallas_double() + jnp.uint32(1),
        )),
    }


@pytest.mark.parametrize("rule", ["PAL001", "PAL002", "PAL003", "PAL004",
                                  "PAL005"])
def test_cli_exits_nonzero_on_rule_fixture(rule, monkeypatch, capsys):
    from bfs_tpu.analysis import __main__ as cli

    monkeypatch.setattr(pal_mod, "KERNEL_SPECS",
                        {rule: _fixture_specs()[rule]})
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    rc = cli.main(["--pallas", "--no-cache", "--no-baseline"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert rule in out.out


def test_cli_pallas_subcommand_and_baseline_accept(monkeypatch, tmp_path,
                                                   capsys):
    """`python -m bfs_tpu.analysis pallas` == `--pallas`; a justified
    baseline entry turns the same fixture run green."""
    from bfs_tpu.analysis import __main__ as cli

    spec_build = _fixture_specs()["PAL002"]
    monkeypatch.setattr(pal_mod, "KERNEL_SPECS", {"PAL002": spec_build})
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    findings = analyze_kernel(spec_build())
    bl = tmp_path / "baseline.txt"
    bl.write_text("".join(
        f"{f.rule}  {f.fingerprint()}  fixture: accepted\n"
        for f in findings
    ))
    rc = cli.main(["pallas", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_pallas_rejects_scoping_flags(capsys):
    from bfs_tpu.analysis import __main__ as cli

    for argv in (["--pallas", "--changed"], ["--pallas", "some/file.py"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, (argv, out.out, out.err)
        assert "cannot be scoped" in out.err
    for argv in (["--ir", "--pallas"], ["--hlo", "--pallas"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2
        assert "one at a time" in out.err


def test_cli_stale_pal_entry_fails_default_surface(monkeypatch, tmp_path,
                                                   capsys):
    """A stale `pal:` fingerprint fails a default-surface --pallas run
    exactly like `ir:`/`hlo:` ones — and other families' entries are
    not this pass's business."""
    from bfs_tpu.analysis import __main__ as cli

    monkeypatch.setattr(pal_mod, "KERNEL_SPECS", _small_registry())
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    bl = tmp_path / "baseline.txt"
    bl.write_text("PAL002  deadbeef0000  a dead pal entry\n")
    rc = cli.main(["--pallas", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "STALE" in out.err
    bl.write_text("HLO003  deadbeef0000  another family's entry\n")
    rc = cli.main(["--pallas", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_pallas_write_baseline_prints_never_clobbers(monkeypatch,
                                                         tmp_path, capsys):
    from bfs_tpu.analysis import __main__ as cli

    monkeypatch.setattr(pal_mod, "KERNEL_SPECS",
                        {"PAL002": _fixture_specs()["PAL002"]})
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    bl = tmp_path / "baseline.txt"
    bl.write_text("TRC001  cafecafe0000  keep me\n")
    rc = cli.main(["--pallas", "--no-cache", "--write-baseline",
                   "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0
    assert "PAL002" in out.out  # candidate line printed
    assert "PAL section" in out.err
    assert bl.read_text() == "TRC001  cafecafe0000  keep me\n"  # untouched


# ---------------------------------------------------------------------------
# CLI: the --all composite surface.
# ---------------------------------------------------------------------------

def test_cli_all_rejects_scoping_and_combinations(capsys):
    from bfs_tpu.analysis import __main__ as cli

    for argv in (["--all", "--changed"], ["--all", "some/file.py"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, (argv, out.out, out.err)
        assert "cannot be scoped" in out.err
    for argv in (["--all", "--ir"], ["--all", "--hlo"],
                 ["--all", "--pallas"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, argv
        assert "one at a time" in out.err
    rc = cli.main(["--all", "--write-baseline"])
    out = capsys.readouterr()
    assert rc == 2
    assert "--write-baseline" in out.err


@pytest.mark.lint_pallas
@pytest.mark.lint_hlo
@pytest.mark.lint_ir
def test_cli_all_green_on_repo(capsys):
    """The pre-merge gate surface: AST + IR + HLO + Pallas in one run,
    clean modulo the committed baseline, exit 0.  Reuses the same
    content-addressed caches the single-pass self-lints populate."""
    from bfs_tpu.analysis import __main__ as cli

    rc = cli.main(["--all"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "analysis[--all]" in out.err
    assert "pal: 6" in out.err


@pytest.mark.lint_pallas
@pytest.mark.lint_hlo
@pytest.mark.lint_ir
def test_cli_all_merges_exit_code_and_skip_exempts_family(monkeypatch,
                                                          tmp_path,
                                                          capsys):
    """One tripping Pallas fixture makes the whole composite non-zero;
    a registry whose kernels all SKIP exempts the PAL family from stale
    enforcement (its baseline entries prove nothing) and the composite
    goes green on the other three passes."""
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis.ir import SkipProgram

    monkeypatch.setattr(pal_mod, "KERNEL_SPECS",
                        {"PAL005": _fixture_specs()["PAL005"]})
    monkeypatch.setattr(pal_mod, "registry_findings",
                        lambda *a, **k: [])
    # Fixture-registry results must not land in the repo's real
    # .bench_cache/pal/ (IR/HLO stay on their real caches — that is
    # the point of the composite being cheap).
    monkeypatch.setattr(pal_mod, "default_cache_dir",
                        lambda root=None: str(tmp_path))
    rc = cli.main(["--all"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert "PAL005" in out.out

    def skipper():
        raise SkipProgram("no router in this fixture")

    skipper.sites = ()
    monkeypatch.setattr(pal_mod, "KERNEL_SPECS", {"fx.skip": skipper})
    rc = cli.main(["--all"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "skipped" in out.err
