"""Golden per-superstep state tests on tinyCG — the automated version of the
paper's hand-verified iteration tables (docs/BigData_Project.pdf §1.4
Tables 3-6, which are literally the reference's problemFile_i files)."""

from bfs_tpu.graph.vertex import initial_state_vertices, state_to_vertices
from bfs_tpu.models.bfs import SuperstepRunner

# Expected problemFile_i contents for tinyCG, source 0, canonical min-parent
# paths.  Neighbour sets are sorted (Java HashSet order is unspecified; any
# order parses identically).
GOLDEN = {
    0: [
        "0|[1, 2, 5]|[0]|0|GRAY",
        "1|[0, 2]|[0]|2147483647|WHITE",
        "2|[0, 1, 3, 4]|[0]|2147483647|WHITE",
        "3|[2, 4, 5]|[0]|2147483647|WHITE",
        "4|[2, 3]|[0]|2147483647|WHITE",
        "5|[0, 3]|[0]|2147483647|WHITE",
    ],
    1: [
        "0|[1, 2, 5]|[0]|0|BLACK",
        "1|[0, 2]|[0, 1]|1|GRAY",
        "2|[0, 1, 3, 4]|[0, 2]|1|GRAY",
        "3|[2, 4, 5]|[0]|2147483647|WHITE",
        "4|[2, 3]|[0]|2147483647|WHITE",
        "5|[0, 3]|[0, 5]|1|GRAY",
    ],
    2: [
        "0|[1, 2, 5]|[0]|0|BLACK",
        "1|[0, 2]|[0, 1]|1|BLACK",
        "2|[0, 1, 3, 4]|[0, 2]|1|BLACK",
        "3|[2, 4, 5]|[0, 2, 3]|2|GRAY",
        "4|[2, 3]|[0, 2, 4]|2|GRAY",
        "5|[0, 3]|[0, 5]|1|BLACK",
    ],
    3: [
        "0|[1, 2, 5]|[0]|0|BLACK",
        "1|[0, 2]|[0, 1]|1|BLACK",
        "2|[0, 1, 3, 4]|[0, 2]|1|BLACK",
        "3|[2, 4, 5]|[0, 2, 3]|2|BLACK",
        "4|[2, 3]|[0, 2, 4]|2|BLACK",
        "5|[0, 3]|[0, 5]|1|BLACK",
    ],
}


def test_golden_superstep_states(tiny_graph):
    assert [
        v.serialize() for v in initial_state_vertices(tiny_graph, 0)
    ] == GOLDEN[0]

    runner = SuperstepRunner(tiny_graph)
    state = runner.init(0)
    level = 0
    while bool(state.changed):
        state = runner.step(state)
        level = int(state.level)
        got = [
            v.serialize()
            for v in state_to_vertices(
                tiny_graph, state.dist, state.parent, state.frontier, source=0
            )
        ]
        assert got == GOLDEN[level], f"superstep {level} state mismatch"
    # Terminates after 3 supersteps with no GRAY left — the reference's
    # contains("GRAY") test goes false (BfsSpark.java:117).
    assert level == 3
    assert all("GRAY" not in line for line in GOLDEN[3])
