"""Persistent layout-bundle cache tests (ISSUE 2 tentpole a): round-trip
bit-identity, corrupted/stale bundle rejection + rebuild, tag aliases, and
hit/miss accounting."""

import json
import os

import numpy as np
import pytest

from bfs_tpu.cache.layout import (
    LayoutCache,
    STORE_VERSION,
    graph_content_hash,
    load_or_build_pull,
    load_or_build_relay,
    pull_key,
    relay_key,
)
from bfs_tpu.graph import benes
from bfs_tpu.graph.ell import pull_to_arrays
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.graph.relay import relay_to_arrays

needs_router = pytest.mark.skipif(
    not benes.native_available(), reason="requires the native benes router"
)


@pytest.fixture
def cache(tmp_path):
    return LayoutCache(str(tmp_path / "layout"))


def test_content_hash_distinguishes_graphs(tiny_graph):
    other = gnm_graph(100, 200, seed=7)
    assert graph_content_hash(tiny_graph) != graph_content_hash(other)
    # Memoized: second call returns the cached digest.
    assert graph_content_hash(tiny_graph) == tiny_graph._content_hash


def test_pull_round_trip_bit_identical(tiny_graph, cache):
    pg, info = load_or_build_pull(tiny_graph, cache=cache)
    assert info["cache"] == "miss"
    pg2, info2 = load_or_build_pull(tiny_graph, cache=cache)
    assert info2["cache"] == "hit"
    # The recorded COLD build time rides along on every warm load.
    assert info2["build_seconds"] == pytest.approx(info["build_seconds"])
    a, b = pull_to_arrays(pg), pull_to_arrays(pg2)
    assert set(a) == set(b)
    for name in a:
        np.testing.assert_array_equal(np.asarray(a[name]), np.asarray(b[name]))


@needs_router
def test_relay_round_trip_bit_identical(medium_graph, cache):
    rg, info = load_or_build_relay(medium_graph, cache=cache)
    assert info["cache"] == "miss"
    rg2, info2 = load_or_build_relay(medium_graph, cache=cache)
    assert info2["cache"] == "hit"
    a, b = relay_to_arrays(rg), relay_to_arrays(rg2)
    assert set(a) == set(b)
    for name in a:
        np.testing.assert_array_equal(
            np.asarray(a[name]), np.asarray(b[name]), err_msg=name
        )
    # Static metadata (NamedTuples/dataclasses) reconstructs exactly.
    assert rg2.net_table == rg.net_table
    assert rg2.vperm_table == rg.vperm_table
    assert rg2.in_classes == rg.in_classes
    assert rg2.out_classes == rg.out_classes


def test_corrupted_array_rejected_and_rebuilt(tiny_graph, cache):
    _, info = load_or_build_pull(tiny_graph, cache=cache)
    key = info["key"]
    path = os.path.join(cache._dir(key), "ell0.npy")
    arr = np.load(path)
    arr[0, 0] += 1
    np.save(path, arr)
    # The tampered field fails its fingerprint; the bundle is dropped...
    assert cache.load(key) is None
    assert not cache.has(key)
    # ...and the next load-or-build silently rebuilds a fresh one.
    pg, info2 = load_or_build_pull(tiny_graph, cache=cache)
    assert info2["cache"] == "miss"
    assert cache.has(key)


def test_truncated_bundle_rejected(tiny_graph, cache):
    _, info = load_or_build_pull(tiny_graph, cache=cache)
    key = info["key"]
    os.remove(os.path.join(cache._dir(key), "ell0.npy"))
    assert cache.load(key) is None


def test_stale_store_version_rejected(tiny_graph, cache):
    _, info = load_or_build_pull(tiny_graph, cache=cache)
    key = info["key"]
    meta_path = os.path.join(cache._dir(key), "meta.json")
    with open(meta_path) as f:
        doc = json.load(f)
    doc["store_version"] = STORE_VERSION + 1
    with open(meta_path, "w") as f:
        json.dump(doc, f)
    assert cache.load(key) is None  # dropped as stale
    _, info2 = load_or_build_pull(tiny_graph, cache=cache)
    assert info2["cache"] == "miss"


def test_keys_cover_params_and_code_version(tiny_graph):
    # Different layout params -> different keys (no aliasing).
    assert pull_key(tiny_graph, 32, 64) != pull_key(tiny_graph, 16, 64)
    assert relay_key(tiny_graph) != pull_key(tiny_graph, 32, 64)
    from bfs_tpu.graph.relay import LAYOUT_VERSION

    assert f"v{LAYOUT_VERSION}" in relay_key(tiny_graph)


def test_tag_alias_probes_warmth(tiny_graph, cache):
    assert cache.resolve_tag("bench_s10") is None
    _, info = load_or_build_pull(tiny_graph, cache=cache, tag="bench_s10")
    assert cache.resolve_tag("bench_s10") == info["key"]
    # A tag whose bundle vanished resolves to None (cold), not a dangle.
    cache.invalidate(info["key"])
    assert cache.resolve_tag("bench_s10") is None


def test_disabled_cache_builds_directly(tiny_graph):
    pg, info = load_or_build_pull(tiny_graph, cache=None)
    assert info["cache"] == "disabled"
    assert pg.num_vertices == tiny_graph.num_vertices
