"""Tests for bfs_tpu.analysis.ir — the IR-grade pass: every rule must
trip on a fixture program and stay quiet on its near-miss, the repo's own
hot-program registry must lint clean modulo the baseline, the
content-addressed result cache must hit on an unchanged tree, and the
CLI must exit non-zero on each rule fixture.

The repo-wide registry runs carry the ``lint_ir`` marker so a quick
``-m 'not lint_ir'`` selection can skip the (cached, but cold-traced)
jax work; plain tier-1 runs them.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from bfs_tpu.analysis import Baseline, default_baseline_path
from bfs_tpu.analysis.ir import (
    Program,
    analyze_ir,
    analyze_program,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
V = 64


def rules_of(findings):
    return sorted({f.rule for f in findings})


def _mesh(shape=(2,), names=("graph",)):
    n = int(np.prod(shape))
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


# ---------------------------------------------------------------------------
# IR001 — donation.
# ---------------------------------------------------------------------------

def _step_like(donate: bool):
    fn = jax.jit(lambda s: s + 1, donate_argnums=0) if donate else jax.jit(
        lambda s: s + 1
    )
    return Program(
        name="fx.step", path="fx.py", fn=fn,
        args=(jnp.zeros(V + 1, jnp.int32),), v_elements=V,
        donate={0: "state"},
    )


def test_ir001_undonated_carry_trips():
    fs = analyze_program(_step_like(donate=False))
    assert rules_of(fs) == ["IR001"]
    # The finding reports the doubled bytes: (V+1) int32 = 260.
    assert "260" in fs[0].message


def test_ir001_near_miss_donated():
    assert analyze_program(_step_like(donate=True)) == []


def test_ir001_scalar_leaves_never_flagged():
    # A pytree carry whose small leaves (level/changed scalars) are not
    # donatable must not trip as long as the V-sized leaves are donated.
    fn = jax.jit(lambda s: (s[0] * 2, s[1] + 1), donate_argnums=0)
    prog = Program(
        name="fx.tree", path="fx.py", fn=fn,
        args=((jnp.zeros(V, jnp.uint32), jnp.int32(0)),),
        v_elements=V, donate={0: "state"},
    )
    assert analyze_program(prog) == []


# ---------------------------------------------------------------------------
# IR002 — host round-trips inside loop bodies.
# ---------------------------------------------------------------------------

def test_ir002_callback_in_loop_trips():
    @jax.jit
    def loopy(x):
        def body(c):
            jax.debug.print("level {}", c[1])
            return c[0] * 2, c[1] + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))

    prog = Program(name="fx.cb", path="fx.py", fn=loopy,
                   args=(jnp.zeros(V, jnp.uint32),), v_elements=V)
    assert rules_of(analyze_program(prog)) == ["IR002"]


def test_ir002_near_miss_callback_outside_loop():
    @jax.jit
    def tail_print(x):
        out = jax.lax.while_loop(
            lambda c: c[1] < 3, lambda c: (c[0] * 2, c[1] + 1), (x, 0)
        )
        jax.debug.print("done {}", out[1])
        return out

    prog = Program(name="fx.cb_ok", path="fx.py", fn=tail_print,
                   args=(jnp.zeros(V, jnp.uint32),), v_elements=V)
    assert analyze_program(prog) == []


# ---------------------------------------------------------------------------
# IR003 — dtype drift.
# ---------------------------------------------------------------------------

def test_ir003_packed_word_widening_trips():
    @jax.jit
    def drift(x):
        def body(c):
            w, i = c
            bad = w.astype(jnp.float32).sum()  # V-sized u32 -> f32
            return w + bad.astype(jnp.uint32), i + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))

    prog = Program(name="fx.drift", path="fx.py", fn=drift,
                   args=(jnp.zeros(V, jnp.uint32),), v_elements=V,
                   packed=True)
    assert rules_of(analyze_program(prog)) == ["IR003"]


def test_ir003_near_miss_scalar_and_int32_masses():
    # The Beamer predicate's int32->float32 scalar masses and a masked
    # int32 out-degree sum are the loop's bread and butter — clean.
    @jax.jit
    def masses(x, outdeg):
        def body(c):
            w, i = c
            fs = (w != 0).sum(dtype=jnp.int32)
            fe = jnp.where(w != 0, outdeg, 0).astype(jnp.float32).sum()
            keep = (fs.astype(jnp.float32) + fe) > 0
            return jnp.where(keep, w, w), i + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))

    prog = Program(
        name="fx.masses", path="fx.py", fn=masses,
        args=(jnp.zeros(V, jnp.uint32), jnp.zeros(V, jnp.int32)),
        v_elements=V, packed=True,
    )
    assert analyze_program(prog) == []


# ---------------------------------------------------------------------------
# IR004 — HBM budget proof.
# ---------------------------------------------------------------------------

def test_ir004_budget_exceeded_trips_and_ample_passes():
    fn = jax.jit(lambda s: s * 2)
    args = (jnp.zeros(4096, jnp.int32),)
    tight = Program(name="fx.tight", path="fx.py", fn=fn, args=args,
                    v_elements=V, budget_bytes=1024)
    ample = Program(name="fx.ample", path="fx.py", fn=fn, args=args,
                    v_elements=V, budget_bytes=1 << 30)
    fs = analyze_program(tight)
    assert rules_of(fs) == ["IR004"]
    assert "cannot fit" in fs[0].message
    assert analyze_program(ample) == []


# ---------------------------------------------------------------------------
# IR005 — collective / mesh-axis correctness.
# ---------------------------------------------------------------------------

def test_ir005_missing_required_exchange_trips():
    mesh = _mesh()

    def no_collective(x):
        return shard_map(lambda xb: xb * 2, mesh=mesh, in_specs=P("graph"),
                         out_specs=P("graph"))(x)

    prog = Program(
        name="fx.nocoll", path="fx.py", fn=jax.jit(no_collective),
        args=(jnp.zeros(V * 2, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )
    fs = analyze_program(prog)
    assert rules_of(fs) == ["IR005"]
    assert "missing" in fs[0].snippet


def test_ir005_out_specs_disagreement_trips():
    mesh = _mesh()

    def sharded_out(x):
        return shard_map(lambda xb: xb * 2, mesh=mesh, in_specs=P("graph"),
                         out_specs=P("graph"))(x)

    prog = Program(
        name="fx.outspec", path="fx.py", fn=jax.jit(sharded_out),
        args=(jnp.zeros(V * 2, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}),
        expected_out_names=(frozenset(),),  # caller expects replicated
    )
    fs = analyze_program(prog)
    assert [f.snippet for f in fs] == ["ir:fx.outspec:out_specs"]


def test_ir005_extra_collective_over_undeclared_axis_trips():
    mesh = _mesh((2, 2), ("batch", "graph"))

    def extra(x):
        def inner(xb):
            merged = jax.lax.psum(xb.astype(jnp.int32), "graph")
            return jax.lax.psum(merged, "batch").astype(jnp.uint32)

        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P())(x)

    prog = Program(
        name="fx.extra", path="fx.py", fn=jax.jit(extra),
        args=(jnp.zeros(V * 16, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}),  # batch is NOT declared
        required_axes=frozenset({"graph"}),
        exchange_dtypes=("uint32", "int32", "bool"),
    )
    assert any(
        f.rule == "IR005" and f.snippet.endswith("extra:batch")
        for f in analyze_program(prog)
    )


def test_ir005_near_miss_declared_exchange_clean():
    mesh = _mesh()

    def merged(x):
        def inner(xb):
            return jax.lax.psum(xb.astype(jnp.int32), "graph").astype(
                jnp.uint32
            )

        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P())(x)

    prog = Program(
        name="fx.ok", path="fx.py", fn=jax.jit(merged),
        args=(jnp.zeros(V * 16, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )
    assert analyze_program(prog) == []


# ---------------------------------------------------------------------------
# IR006 — exchange payload format.
# ---------------------------------------------------------------------------

def _exchange_prog(dtype, name):
    mesh = _mesh()

    def prog_fn(x):
        def inner(xb):
            return jax.lax.psum(xb.astype(dtype), "graph").astype(
                jnp.float32
            )

        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P())(x)

    return Program(
        name=name, path="fx.py", fn=jax.jit(prog_fn),
        args=(jnp.zeros(V * 16, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )


def test_ir006_widened_exchange_payload_trips():
    fs = analyze_program(_exchange_prog(jnp.float32, "fx.fat"))
    assert rules_of(fs) == ["IR006"]
    assert "float32" in fs[0].message


def test_ir006_near_miss_packed_word_exchange():
    mesh = _mesh()

    def ok(x):
        def inner(xb):
            return jax.lax.psum(xb.astype(jnp.int32), "graph").astype(
                jnp.uint32
            )

        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P())(x)

    prog = Program(
        name="fx.okex", path="fx.py", fn=jax.jit(ok),
        args=(jnp.zeros(V * 16, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )
    assert analyze_program(prog) == []


def test_ir006_control_scalar_reduce_never_flagged():
    # The `changed` termination all-reduce is a 4-byte control scalar —
    # under the exchange floor, any dtype.
    mesh = _mesh()

    def term(x):
        def inner(xb):
            changed = jax.lax.pmax((xb != 0).any().astype(jnp.float32),
                                   "graph")
            return xb * changed.astype(jnp.uint32)

        return shard_map(inner, mesh=mesh, in_specs=P("graph"),
                         out_specs=P("graph"))(x)

    prog = Program(
        name="fx.term", path="fx.py", fn=jax.jit(term),
        args=(jnp.zeros(V * 2, jnp.uint32),), v_elements=V,
        mesh_axes=frozenset({"graph"}), required_axes=frozenset({"graph"}),
    )
    assert analyze_program(prog) == []


# ---------------------------------------------------------------------------
# IR000 — unloadable programs fail loudly.
# ---------------------------------------------------------------------------

def test_ir000_unlowerable_program_is_an_error():
    def broken(x):
        raise TypeError("deliberately unlowerable")

    prog = Program(name="fx.broken", path="fx.py", fn=broken,
                   args=(jnp.zeros(4, jnp.int32),), v_elements=V)
    fs = analyze_program(prog)
    assert rules_of(fs) == ["IR000"]


# ---------------------------------------------------------------------------
# The repo registry: self-lint + cache.
# ---------------------------------------------------------------------------

@pytest.mark.lint_ir
def test_repo_ir_self_lint_clean_modulo_baseline():
    """Every declared hot program lowers and passes the IR rules (the
    tier-1 'what XLA sees is clean' gate — the cached twin of the CLI's
    default run)."""
    findings, meta = analyze_ir(use_cache=True)
    # Hot-coverage pin (extended for the ISSUE 11 exchange programs):
    # the registry must keep declaring at least this many hot programs,
    # and the sharded relay family — dense, the exchange density cond,
    # and the adjacency-shipping push/direction flavor — must all be in
    # it (built or explicitly skipped, never silently dropped).
    assert len(meta["programs"]) + len(meta["skipped"]) >= 28, meta
    covered = set(meta["programs"]) | set(meta["skipped"])
    for name in ("sharded.relay_dense", "sharded.relay_exchange_auto",
                 "sharded.relay_push"):
        assert name in covered, (name, meta)
    baseline = Baseline.load(default_baseline_path())
    fresh = [f for f in findings if not baseline.accepts(f)]
    assert fresh == [], "\n".join(f.render() for f in fresh)
    # The donation dogfood (this PR's fix) must stay fixed: no program
    # may report an un-donated carry ever again without a baseline entry.
    assert not any(f.rule == "IR001" for f in findings)


@pytest.mark.lint_ir
def test_ir_result_cache_hits_on_unchanged_tree(tmp_path):
    f1, m1 = analyze_ir(use_cache=True, cache_dir=str(tmp_path))
    assert m1["cache"] == "miss"
    f2, m2 = analyze_ir(use_cache=True, cache_dir=str(tmp_path))
    assert m2["cache"] == "hit"
    assert [f.fingerprint() for f in f2] == [f.fingerprint() for f in f1]
    assert any(name.startswith("ir_") for name in os.listdir(tmp_path))


def test_ir_skip_records_program(monkeypatch):
    from bfs_tpu.analysis import ir as ir_mod

    def skipper():
        raise ir_mod.SkipProgram("no mesh here")

    findings, meta = analyze_ir({"fx.skipped": skipper})
    assert findings == []
    assert meta["skipped"] == {"fx.skipped": "no mesh here"}
    assert meta["cache"] == "off"  # custom specs are never cached


# ---------------------------------------------------------------------------
# Donation is real at runtime: a stepped state is consumed.
# ---------------------------------------------------------------------------

def test_superstep_state_buffers_donated(tiny_graph):
    from bfs_tpu.models.bfs import SuperstepRunner

    runner = SuperstepRunner(tiny_graph, engine="push")
    s0 = runner.init(0)
    s1 = runner.step(s0)
    assert int(s1.level) == 1
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.device_get(s0.dist))


# ---------------------------------------------------------------------------
# CLI: the --ir path exits non-zero on each rule fixture.
# ---------------------------------------------------------------------------

def _fixture_specs():
    mesh_ok = len(jax.devices()) >= 2
    specs = {
        "IR001": lambda: _step_like(donate=False),
        "IR004": lambda: Program(
            name="fx.tight", path="fx.py", fn=jax.jit(lambda s: s * 2),
            args=(jnp.zeros(4096, jnp.int32),), v_elements=V,
            budget_bytes=1024,
        ),
    }

    @jax.jit
    def loopy(x):
        def body(c):
            jax.debug.print("lvl {}", c[1])
            return c[0] * 2, c[1] + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))

    specs["IR002"] = lambda: Program(
        name="fx.cb", path="fx.py", fn=loopy,
        args=(jnp.zeros(V, jnp.uint32),), v_elements=V,
    )

    @jax.jit
    def drift(x):
        def body(c):
            w, i = c
            return w + w.astype(jnp.float32).sum().astype(jnp.uint32), i + 1

        return jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))

    specs["IR003"] = lambda: Program(
        name="fx.drift", path="fx.py", fn=drift,
        args=(jnp.zeros(V, jnp.uint32),), v_elements=V, packed=True,
    )
    if mesh_ok:
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("graph",))

        def no_collective(x):
            return shard_map(
                lambda xb: xb * 2, mesh=mesh, in_specs=P("graph"),
                out_specs=P("graph"),
            )(x)

        specs["IR005"] = lambda: Program(
            name="fx.nocoll", path="fx.py", fn=jax.jit(no_collective),
            args=(jnp.zeros(V * 2, jnp.uint32),), v_elements=V,
            mesh_axes=frozenset({"graph"}),
            required_axes=frozenset({"graph"}),
        )
        specs["IR006"] = lambda: _exchange_prog(jnp.float32, "fx.fat")
    return specs


@pytest.mark.parametrize("rule", ["IR001", "IR002", "IR003", "IR004",
                                  "IR005", "IR006"])
def test_cli_exits_nonzero_on_rule_fixture(rule, monkeypatch, capsys):
    specs = _fixture_specs()
    if rule not in specs:
        pytest.skip("needs 2 devices")
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import ir as ir_mod

    monkeypatch.setattr(ir_mod, "PROGRAM_SPECS", {rule: specs[rule]})
    rc = cli.main(["--ir", "--no-cache", "--no-baseline"])
    out = capsys.readouterr()
    assert rc == 1, out.out + out.err
    assert rule in out.out


def test_cli_ir_subcommand_and_baseline_accept(monkeypatch, tmp_path,
                                               capsys):
    """`python -m bfs_tpu.analysis ir` == `--ir`; a justified baseline
    entry turns the same fixture run green."""
    from bfs_tpu.analysis import __main__ as cli
    from bfs_tpu.analysis import ir as ir_mod

    specs = _fixture_specs()
    monkeypatch.setattr(ir_mod, "PROGRAM_SPECS", {"IR001": specs["IR001"]})
    [finding] = analyze_program(specs["IR001"]())
    bl = tmp_path / "baseline.txt"
    bl.write_text(
        f"{finding.rule}  {finding.fingerprint()}  fixture: accepted\n"
    )
    rc = cli.main(["ir", "--no-cache", "--baseline", str(bl)])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err


def test_cli_ir_rejects_scoping_flags(capsys):
    """--ir always runs the whole registry; silently dropping a path or
    --changed scope would report a result the user never asked for."""
    from bfs_tpu.analysis import __main__ as cli

    for argv in (["--ir", "--changed"], ["--ir", "some/file.py"]):
        rc = cli.main(argv)
        out = capsys.readouterr()
        assert rc == 2, (argv, out.out, out.err)
        assert "cannot be scoped" in out.err


def test_ir_finding_fingerprint_is_line_drift_proof():
    [f] = analyze_program(_step_like(donate=False))
    # Fingerprints hash (rule, path, ir:<program>:<detail>) — no line
    # numbers involved, so source drift can never invalidate an entry.
    assert f.snippet.startswith("ir:fx.step:donate:")
    assert f.line == 0
