"""Graph500-style harness (ISSUE 16): ``tools/graph500_run.py``.

Covers: the official per-kernel statistics block (quartiles, mean/stddev
over time and nedge, TEPS quartiles, harmonic mean/stddev of TEPS) on
hand-checkable inputs; degree-filtered deterministic root sampling; an
end-to-end scale run whose output carries the official keys and whose
capture lines are ledger-shaped JSONL; and journal resume (a re-run of a
completed scale replays the journaled document instead of recomputing).
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from conftest import REPO_ROOT

from bfs_tpu.graph.csr import Graph

_spec = importlib.util.spec_from_file_location(
    "graph500_run", os.path.join(REPO_ROOT, "tools", "graph500_run.py")
)
g5 = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(g5)


# ------------------------------------------------------------- statistics --
def test_kernel_stats_official_keys_and_harmonic_mean():
    times = np.array([1.0, 2.0, 4.0, 8.0])
    nedges = np.full(4, 100.0)
    s = g5.kernel_stats(times, nedges)
    for block in ("time", "nedge", "TEPS"):
        for stat in ("min", "firstquartile", "median", "thirdquartile",
                     "max"):
            assert f"{stat}_{block}" in s
    for block in ("time", "nedge"):
        assert f"mean_{block}" in s and f"stddev_{block}" in s
    # TEPS aggregates harmonically: 4 / sum(t/100) = 4 / 0.15.
    assert s["harmonic_mean_TEPS"] == pytest.approx(4 / 0.15)
    assert s["harmonic_stddev_TEPS"] > 0
    assert s["min_time"] == 1.0 and s["max_time"] == 8.0
    assert s["median_nedge"] == 100.0


def test_kernel_stats_single_root():
    s = g5.kernel_stats(np.array([2.0]), np.array([50.0]))
    assert s["stddev_time"] == 0.0
    assert s["harmonic_mean_TEPS"] == pytest.approx(25.0)
    assert s["harmonic_stddev_TEPS"] == 0.0


def test_format_output_official_lines():
    s = g5.kernel_stats(np.array([1.0, 2.0]), np.array([10.0, 10.0]))
    text = g5.format_output(5, 16, 2, 0.1, 0.2, {"bfs": s, "sssp": s})
    assert "SCALE: 5" in text
    assert "edgefactor: 16" in text
    assert "NBFS: 2" in text
    assert "construction_time: 0.2" in text
    assert "bfs validation: PASSED" in text
    assert "bfs  harmonic_mean_TEPS:" in text
    assert "sssp  median_time:" in text


# ----------------------------------------------------------------- roots --
def test_sample_roots_degree_filtered_and_deterministic():
    # Vertex 3 is isolated: it must never be sampled as a search key.
    edges = np.array([[0, 1], [1, 2], [2, 0]], dtype=np.int32)
    g = Graph.from_undirected_edges(4, edges)
    roots = g5.sample_roots(g, nbfs=3, seed=7)
    assert 3 not in roots.tolist()
    assert len(set(roots.tolist())) == len(roots)
    np.testing.assert_array_equal(roots, g5.sample_roots(g, nbfs=3, seed=7))


# ------------------------------------------------------------ end to end --
@pytest.mark.algo_smoke
def test_main_end_to_end(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BFS_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    out = tmp_path / "official.txt"
    cap = tmp_path / "capture.json"
    rc = g5.main([
        "--scales", "5", "--roots", "3", "--seed", "2",
        "--max-weight", "31", "--out", str(out), "--capture", str(cap),
    ])
    assert rc == 0
    text = out.read_text()
    assert "SCALE: 5" in text
    assert "bfs validation: PASSED" in text
    assert "sssp validation: PASSED" in text
    assert "sssp  harmonic_mean_TEPS:" in text
    lines = [json.loads(l) for l in cap.read_text().splitlines()]
    assert {l["metric"] for l in lines} == {
        "graph500_s5_bfs_harmonic_TEPS",
        "graph500_s5_sssp_harmonic_TEPS",
    }
    for line in lines:
        assert set(line) == {
            "metric", "value", "unit", "vs_baseline", "details"
        }
        assert line["unit"] == "TEPS" and line["value"] > 0
        assert line["details"]["validation"] == "PASSED"
    capsys.readouterr()  # drain the official block printed to stdout


def test_journal_resume_replays_completed_scale(tmp_path):
    from bfs_tpu.resilience.journal import RunJournal

    cfg = {"tool": "graph500_run", "scales": [5], "edgefactor": 8,
           "roots": 2, "seed": 3, "max_weight": 31}
    jr = RunJournal.open_for(str(tmp_path), cfg)
    doc1 = g5.run_scale(5, edgefactor=8, nbfs=2, seed=3, max_weight=31,
                        jr=jr)
    doc2 = g5.run_scale(5, edgefactor=8, nbfs=2, seed=3, max_weight=31,
                        jr=jr)
    # Bit-identical wall-clock floats prove the journal replayed the
    # document rather than re-running the kernels.
    assert doc2 == doc1
    jr.close()
    jr2 = RunJournal.open_for(str(tmp_path), cfg)
    assert g5.run_scale(5, edgefactor=8, nbfs=2, seed=3, max_weight=31,
                        jr=jr2) == doc1
    jr2.close()
