"""Element-major batched multi-source relay vs the oracle and the other
batched modes (BreadthFirstPaths.java:114-132 semantics x BASELINE.json
config 5)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bfs_tpu.graph import benes  # noqa: E402

if not benes.native_available():  # pragma: no cover
    pytest.skip("native benes router unavailable", allow_module_level=True)

from bfs_tpu.graph.csr import Graph  # noqa: E402
from bfs_tpu.models.bfs import RelayEngine  # noqa: E402
from bfs_tpu.oracle.bfs import canonical_bfs  # noqa: E402


def _random_graph(seed, v=1500, ne=5000):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, v, ne)
    w = rng.integers(0, v, ne)
    keep = u != w
    u, w = u[keep], w[keep]
    return Graph(v, np.concatenate([u, w]), np.concatenate([w, u])), rng


def test_elem_64_sources_match_oracle():
    g, rng = _random_graph(21, v=2500, ne=7000)
    eng = RelayEngine(g)
    sources = rng.choice(g.num_vertices, size=64, replace=False).astype(np.int32)
    mr = eng.run_multi_elem(sources)
    assert mr.dist.shape == (64, g.num_vertices)
    for i in (0, 1, 17, 31, 32, 40, 63):  # both uint32 groups
        od, op = canonical_bfs(g, int(sources[i]))
        np.testing.assert_array_equal(mr.dist[i], od)
        np.testing.assert_array_equal(mr.parent[i], op)


def test_elem_matches_vmapped_mode_bitexact():
    g, rng = _random_graph(33)
    eng = RelayEngine(g)
    sources = rng.choice(g.num_vertices, size=32, replace=False).astype(np.int32)
    a = eng.run_multi_elem(sources)
    b = eng.run_multi(sources)
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)


def test_elem_repeated_and_batch_rules():
    g, rng = _random_graph(44)
    eng = RelayEngine(g)
    with pytest.raises(ValueError):
        eng.run_multi_elem([1, 2, 3])  # not a multiple of 32
    sources = np.array([7] * 16 + [11] * 16, dtype=np.int32)  # duplicates OK
    mr = eng.run_multi_elem(sources)
    od7, _ = canonical_bfs(g, 7)
    od11, _ = canonical_bfs(g, 11)
    np.testing.assert_array_equal(mr.dist[0], od7)
    np.testing.assert_array_equal(mr.dist[15], od7)
    np.testing.assert_array_equal(mr.dist[16], od11)


def test_elem_deep_graph_falls_back_to_vmapped():
    """Eccentricity > MAX_ELEM_LEVELS (31): the bit-sliced distance planes
    cannot converge, so run_multi_elem must detect the unconverged flag and
    fall back to the vmapped engine instead of silently truncating
    (ADVICE.md round 3, medium)."""
    # Path graph 0-1-2-...-99: depth 99 from vertex 0.
    n = 100
    u = np.arange(n - 1, dtype=np.int64)
    w = u + 1
    g = Graph(n, np.concatenate([u, w]), np.concatenate([w, u]))
    eng = RelayEngine(g)
    sources = np.zeros(32, dtype=np.int32)
    mr = eng.run_multi_elem(sources)
    od, op = canonical_bfs(g, 0)
    np.testing.assert_array_equal(mr.dist[0], od)   # full depth, no truncation
    np.testing.assert_array_equal(mr.parent[0], op)
    assert mr.dist[0].max() == n - 1

    # An explicit max_levels request still truncates (caller asked for it).
    state = eng.run_multi_elem_device(sources, max_levels=5)
    assert bool(np.asarray(state.changed))


def test_elem_eccentricity_exactly_31_converges():
    """Depth exactly MAX_ELEM_LEVELS (31): representable in the distance
    planes; the extra confirming superstep must prove convergence instead of
    triggering the fallback (code-review round 4)."""
    n = 32  # path 0-1-...-31: ecc(0) = 31
    u = np.arange(n - 1, dtype=np.int64)
    w = u + 1
    g = Graph(n, np.concatenate([u, w]), np.concatenate([w, u]))
    eng = RelayEngine(g)
    sources = np.zeros(32, dtype=np.int32)
    state = eng.run_multi_elem_device(sources)
    assert not bool(np.asarray(state.changed))  # converged, no fallback
    mr = eng.run_multi_elem(sources)
    od, op = canonical_bfs(g, 0)
    np.testing.assert_array_equal(mr.dist[0], od)
    np.testing.assert_array_equal(mr.parent[0], op)
    assert mr.dist[0].max() == 31
