"""BFS_TPU_BUILD_LOG latch: reversible and idempotent (ADVICE.md round-5
finding #3 — previously a one-way latch that could double-install the
handler under concurrent first builds)."""

import logging

from bfs_tpu.graph import relay


def _reset(monkeypatch):
    monkeypatch.setattr(relay, "_build_log_handler", None)
    monkeypatch.setattr(relay, "_build_log_prev_level", None)


def test_enable_is_idempotent(monkeypatch):
    _reset(monkeypatch)
    handlers_before = list(relay.logger.handlers)
    monkeypatch.setenv("BFS_TPU_BUILD_LOG", "1")
    relay._ensure_build_log()
    relay._ensure_build_log()  # second call must not add a second handler
    added = [h for h in relay.logger.handlers if h not in handlers_before]
    assert len(added) == 1
    assert relay.logger.level == logging.INFO
    monkeypatch.setenv("BFS_TPU_BUILD_LOG", "0")
    relay._ensure_build_log()
    assert relay.logger.handlers == handlers_before


def test_disable_restores_previous_level(monkeypatch):
    _reset(monkeypatch)
    relay.logger.setLevel(logging.WARNING)  # application-configured level
    try:
        monkeypatch.setenv("BFS_TPU_BUILD_LOG", "1")
        relay._ensure_build_log()
        assert relay.logger.level == logging.INFO
        monkeypatch.setenv("BFS_TPU_BUILD_LOG", "0")
        relay._ensure_build_log()
        assert relay.logger.level == logging.WARNING  # restored, not NOTSET
        # Disabled and already clean: a further call is a no-op.
        relay._ensure_build_log()
        assert relay.logger.level == logging.WARNING
    finally:
        relay.logger.setLevel(logging.NOTSET)


def test_off_flag_never_touches_foreign_config(monkeypatch):
    _reset(monkeypatch)
    foreign = logging.NullHandler()
    relay.logger.addHandler(foreign)
    try:
        relay.logger.setLevel(logging.ERROR)
        monkeypatch.setenv("BFS_TPU_BUILD_LOG", "0")
        relay._ensure_build_log()
        assert foreign in relay.logger.handlers
        assert relay.logger.level == logging.ERROR
    finally:
        relay.logger.removeHandler(foreign)
        relay.logger.setLevel(logging.NOTSET)
