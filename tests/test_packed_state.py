"""Packed ``level:6 | parent:26`` state (ops/packed.py): bit-exact parity
vs the host oracle across all engines, the level-overflow sentinel +
fallback chain, per-shard class balance with the asserted padded-work
ratio, and the phase ledger's halved state-update byte accounting."""

import numpy as np
import pytest

import jax.numpy as jnp

from bfs_tpu.graph import benes
from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.ops.packed import (
    INT32_MAX,
    PACKED_MAX_LEVELS,
    PACKED_SENTINEL,
    pack_host,
    packed_parent_fits,
    packed_rank_fits,
    packed_truncated,
    unpack_host,
)
from bfs_tpu.oracle.bfs import canonical_bfs, check

needs_native = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


# ---- the word format --------------------------------------------------------

def test_pack_unpack_roundtrip_and_sentinel():
    dist = np.array([0, 5, PACKED_MAX_LEVELS, INT32_MAX, 1], np.int32)
    parent = np.array([3, (1 << 26) - 1, 0, 12345, 7], np.int32)
    w = pack_host(dist, parent)
    assert w[3] == PACKED_SENTINEL  # unreached -> lattice top
    d2, p2 = unpack_host(w)
    np.testing.assert_array_equal(d2, dist)
    np.testing.assert_array_equal(
        p2, np.where(dist == INT32_MAX, -1, parent)
    )


def test_packed_word_order_is_lexicographic():
    """level major, parent minor: the min-merge prefers earlier levels and,
    within a level, the smaller parent — the canonical tie-break."""
    a = pack_host(np.array([2], np.int32), np.array([9], np.int32))[0]
    b = pack_host(np.array([3], np.int32), np.array([0], np.int32))[0]
    c = pack_host(np.array([2], np.int32), np.array([4], np.int32))[0]
    assert a < b and c < a and min(a, b, c) == c
    assert min(int(PACKED_SENTINEL), int(a)) == int(a)


def test_truncation_predicate():
    assert packed_truncated(True, PACKED_MAX_LEVELS, 10**6)
    assert not packed_truncated(False, PACKED_MAX_LEVELS, 10**6)
    assert not packed_truncated(True, 3, 10**6)
    # the caller's own max_levels, not the cap, stopped the loop:
    assert not packed_truncated(True, 40, 40)


def test_fits_guards():
    assert packed_parent_fits(1 << 26)
    assert not packed_parent_fits((1 << 26) + 1)


# ---- engine parity: packed vs unpacked vs oracle ----------------------------

@needs_native
def test_relay_packed_matches_unpacked_and_oracle(monkeypatch):
    from bfs_tpu.models.bfs import RelayEngine

    g = rmat_graph(9, 8, seed=5)
    monkeypatch.setenv("BFS_TPU_PACKED", "1")
    eng_p = RelayEngine(g)
    assert eng_p.packed
    monkeypatch.setenv("BFS_TPU_PACKED", "0")
    eng_u = RelayEngine(g)
    assert not eng_u.packed
    for s in (0, 17, 300):
        rp, ru = eng_p.run(s), eng_u.run(s)
        dist, parent = canonical_bfs(g, s)
        np.testing.assert_array_equal(rp.dist, dist)
        np.testing.assert_array_equal(rp.parent, parent)
        np.testing.assert_array_equal(ru.dist, dist)
        np.testing.assert_array_equal(ru.parent, parent)
        assert check(g, rp.dist, rp.parent, s) == []


@needs_native
def test_relay_packed_level_overflow_falls_back():
    """A diameter-69 path exceeds the 6-bit level field: the packed run
    must detect the cap exit and re-run unpacked, bit-exact."""
    from bfs_tpu.models.bfs import RelayEngine

    g = path_graph(70)
    eng = RelayEngine(g)  # sparse_hybrid on: covers the packed sparse path
    assert eng.packed
    r = eng.run(0)
    assert r.dist.tolist() == list(range(70))
    assert check(g, r.dist, r.parent, 0) == []
    # the raw packed program really was capped (sanity on the predicate)
    assert r.num_levels > PACKED_MAX_LEVELS


@needs_native
def test_relay_multi_packed_fallback_chain():
    """elem mode (31-level planes) -> packed vmapped (62) -> unpacked:
    each rung of the fallback chain returns oracle-exact trees."""
    from bfs_tpu.models.bfs import RelayEngine

    g = path_graph(70)
    eng = RelayEngine(g, sparse_hybrid=False)
    sources = np.arange(32, dtype=np.int32)
    mr = eng.run_multi_elem(sources)  # falls all the way back
    for i, s in enumerate(sources):
        dist, parent = canonical_bfs(g, int(s))
        np.testing.assert_array_equal(mr.dist[i], dist)
        np.testing.assert_array_equal(mr.parent[i], parent)


def test_pull_push_packed_deep_graph_falls_back():
    from bfs_tpu.models.bfs import bfs

    g = path_graph(70)
    for engine in ("pull", "push"):
        r = bfs(g, 0, engine=engine)
        assert r.dist.tolist() == list(range(70)), engine
        assert check(g, r.dist, r.parent, 0) == [], engine


def test_multisource_packed_deep_graph_falls_back():
    from bfs_tpu.models.multisource import bfs_multi

    g = path_graph(70)
    mr = bfs_multi(g, [0, 65], engine="pull")
    d0, p0 = canonical_bfs(g, 0)
    d1, p1 = canonical_bfs(g, 65)
    np.testing.assert_array_equal(mr.dist[0], d0)
    np.testing.assert_array_equal(mr.parent[0], p0)
    np.testing.assert_array_equal(mr.dist[1], d1)
    np.testing.assert_array_equal(mr.parent[1], p1)


@needs_native
def test_adj_rank_flavor_inverts_slots():
    """The packed sparse path's per-edge ranks reconstruct the layout's
    slots exactly through the static vertex tables."""
    from bfs_tpu.graph.relay import _vertex_tables, build_relay_graph
    from bfs_tpu.models.bfs import _adj_ranks

    g = rmat_graph(8, 8, seed=3)
    rg = build_relay_graph(g)
    ranks = _adj_ranks(rg)
    base1, stride1 = _vertex_tables(list(rg.in_classes), rg.vr)
    rebuilt = base1[rg.adj_dst] + ranks * stride1[rg.adj_dst]
    np.testing.assert_array_equal(rebuilt, rg.adj_slot)
    widths = np.array([0] * rg.vr)
    for cs in rg.in_classes:
        widths[cs.va : cs.vb] = cs.width
    assert (ranks < widths[rg.adj_dst]).all() and (ranks >= 0).all()


# ---- per-shard class balance (sharded relay) --------------------------------

def _skewed_fixture(v: int = 512):
    """Degrees correlated with vertex id: the upper half has in-degree 16,
    the lower half 1 — a contiguous-id partition concentrates each class
    in half the shards, the exact shape behind the x8 padded-work
    amplification (VERDICT r5 weak #5)."""
    half = v // 2
    dst_hi = np.repeat(np.arange(half, v, dtype=np.int64), 16)
    src_hi = (dst_hi * 7 + np.tile(np.arange(16), half)) % half
    dst_lo = np.arange(half, dtype=np.int64)
    src_lo = (dst_lo * 5 + 3) % v
    src = np.concatenate([src_hi, dst_lo * 0 + src_lo])
    dst = np.concatenate([dst_hi, dst_lo])
    return Graph(v, src.astype(np.int32), dst.astype(np.int32))


def _old_unified_envelope(g, n):
    """The pre-change layout arithmetic: contiguous original-id ownership,
    per-width counts maxed over shards — the baseline the balanced
    partition must beat."""
    from bfs_tpu.graph.relay import (
        _build_classes,
        _class_width,
        _round32,
    )

    v = g.num_vertices
    indeg = np.bincount(g.dst, minlength=v)
    in_w = _class_width(indeg)
    vblock = max((v + n - 1) // n, 1)
    shard_of = np.minimum(np.arange(v) // vblock, n - 1)
    widths = np.unique(in_w)
    counts = np.stack(
        [
            np.bincount(
                np.searchsorted(widths, in_w[shard_of == s]),
                minlength=widths.shape[0],
            )
            for s in range(n)
        ],
        axis=1,
    )
    classes = _build_classes(widths, counts.max(axis=1))
    return _round32(classes[-1].vb), classes[-1].sb  # (block, m1)


@needs_native
def test_sharded_per_shard_classes_shrink_padded_slots():
    """The acceptance assertion: per-shard slot count strictly below the
    unified-max baseline on the skewed fixture, at x2 and x8."""
    from bfs_tpu.graph.relay import build_sharded_relay_graph

    g = _skewed_fixture()
    for n in (2, 8):
        srg = build_sharded_relay_graph(g, n)
        old_block, old_m1 = _old_unified_envelope(g, n)
        assert srg.m1 < old_m1, (n, srg.m1, old_m1)
        assert srg.block <= old_block, (n, srg.block, old_block)
    # monotone padded work: total slots at x8 do not exceed x2's total
    m1_2 = build_sharded_relay_graph(g, 2).m1 * 2
    m1_8 = build_sharded_relay_graph(g, 8).m1 * 8
    assert m1_8 <= 2 * m1_2  # sub-linear blowup, not the old x(n) one


def _simulate_sharded_relay(g, srg, source, packed):
    """Host-side lock-step simulation of the sharded relay program — the
    exact per-shard pipeline (vperm -> broadcast -> net -> masked row-min
    -> state update -> frontier exchange) minus the collectives, so the
    per-shard layouts and BOTH carry flavors are exercised on any jax
    (the shard_map program itself needs a multi-device mesh).  Returns
    original-id (dist, parent) via the real map-back."""
    from bfs_tpu.graph.relay import valid_slot_words
    from bfs_tpu.ops import relay as R
    from bfs_tpu.ops.packed import level_word
    from bfs_tpu.parallel.sharded import _relay_map_back

    n, block = srg.num_shards, srg.block
    nw = block // 32
    src_new = int(srg.old2new[source])
    valid = [
        jnp.asarray(valid_slot_words(srg.src_l1[s], srg.net_size))
        for s in range(n)
    ]
    fw_host = np.zeros(n * nw, np.uint32)
    fw_host[src_new >> 5] |= np.uint32(1) << (src_new & 31)
    fw = jnp.asarray(fw_host)
    if packed:
        pk = [np.full(block, PACKED_SENTINEL, np.uint32) for _ in range(n)]
        pk[src_new // block][src_new % block] = 0
        pk = [jnp.asarray(x) for x in pk]
    else:
        dist = [np.full(block, INT32_MAX, np.int32) for _ in range(n)]
        par = [np.full(block, -1, np.int32) for _ in range(n)]
        dist[src_new // block][src_new % block] = 0
        par[src_new // block][src_new % block] = src_new
        dist = [jnp.asarray(d) for d in dist]
        par = [jnp.asarray(p) for p in par]
    level, changed = 0, True
    while changed and level < PACKED_MAX_LEVELS:
        level += 1
        imp_words, changed = [], False
        for s in range(n):
            zpad = jnp.zeros(srg.vperm_size // 32 - n * nw, jnp.uint32)
            x = jnp.concatenate([fw, zpad])
            y = R.apply_benes_std(
                x, jnp.asarray(srg.vperm_masks[s]), srg.vperm_table,
                srg.vperm_size,
            )
            l2 = R.broadcast_l2(
                y, srg.out_classes, srg.net_size, srg.out_space
            )
            l1 = R.apply_benes_std(
                l2, jnp.asarray(srg.net_masks[s]), srg.net_table,
                srg.net_size,
            )
            if packed:
                cand = R.rowmin_ranks(l1, valid[s], srg.in_classes, block)
                pk2 = jnp.minimum(pk[s], cand | level_word(jnp.int32(level)))
                improved = pk2 != pk[s]
                pk[s] = pk2
            else:
                cand = R.rowmin_candidates(
                    l1, valid[s], srg.in_classes, block
                )
                improved = (cand != INT32_MAX) & (dist[s] == INT32_MAX)
                dist[s] = jnp.where(improved, level, dist[s])
                par[s] = jnp.where(improved, cand, par[s])
            imp_words.append(R.pack_std(improved))
            changed = changed or bool(improved.any())
        fw = jnp.concatenate(imp_words)  # the all-gather, minus the mesh
    if packed:
        pairs = [
            R.unpack_relay_packed(pk[s], srg.in_classes, block)
            for s in range(n)
        ]
        dist = np.concatenate([np.asarray(d) for d, _ in pairs])
        par = np.concatenate([np.asarray(p) for _, p in pairs])
    else:
        dist = np.concatenate([np.asarray(d) for d in dist])
        par = np.concatenate([np.asarray(p) for p in par])
    return _relay_map_back(srg, dist, par, source)


@needs_native
@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_sharded_relay_packed_parity(num_shards):
    """x1/x2/x8 parity on the balanced per-shard layouts, BOTH carry
    flavors, dist AND parent bit-exact vs the oracle — on the skewed
    fixture plus an R-MAT."""
    from bfs_tpu.graph.relay import build_sharded_relay_graph

    for g, source in ((_skewed_fixture(), 3), (rmat_graph(8, 8, seed=21), 0)):
        srg = build_sharded_relay_graph(g, num_shards)
        d_o, p_o = canonical_bfs(g, source)
        for packed in (False, True):
            d, p = _simulate_sharded_relay(g, srg, source, packed)
            np.testing.assert_array_equal(d, d_o)
            np.testing.assert_array_equal(p, p_o)


def _mesh_relay_available() -> bool:
    """The shard_map relay program runs through the version-spanning shim
    (bfs_tpu/parallel/compat.py) on every supported jax — the old
    jax.shard_map axis_names gate is retired with it."""
    from bfs_tpu.parallel.compat import shard_map_available

    return shard_map_available()


@needs_native
@pytest.mark.skipif(
    not _mesh_relay_available(),
    reason="jax.shard_map (axis_names API) unavailable",
)
@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_sharded_relay_packed_parity_on_mesh(num_shards):
    """The real shard_map program on the virtual CPU mesh (runs where the
    harness jax has the new mesh API; the simulation twin above covers
    the math everywhere)."""
    from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

    g = _skewed_fixture()
    mesh = make_mesh(graph=num_shards)
    res = bfs_sharded(g, 3, mesh=mesh, engine="relay")
    d_o, p_o = canonical_bfs(g, 3)
    np.testing.assert_array_equal(res.dist, d_o)
    np.testing.assert_array_equal(res.parent, p_o)


# ---- the ledger's byte accounting -------------------------------------------

@needs_native
def test_phase_ledger_state_bytes_halved():
    """CPU-runnable microbench (acceptance): the ledger measures every
    phase and its analytic accounting shows the dist/parent state-update
    HBM bytes exactly halved vs the unpacked layout."""
    from bfs_tpu.models.bfs import RelayEngine
    from bfs_tpu.profiling import state_update_bytes, superstep_phase_ledger

    g = gnm_graph(400, 3000, seed=2)
    eng = RelayEngine(g, sparse_hybrid=False)
    ledger = superstep_phase_ledger(eng, loops=2, repeats=1)
    for phase in ("vperm", "broadcast", "net_apply", "rowmin",
                  "state_update", "full_superstep"):
        assert np.isfinite(ledger["phases"][phase]["seconds"])
    su = ledger["phases"]["state_update"]
    assert ledger["packed_state"] == eng.packed
    assert su["dist_parent_bytes_ratio"] == 2.0
    pb, ub = su["packed"]["bytes"], su["unpacked"]["bytes"]
    assert ub["dist_parent_read"] == 2 * pb["dist_parent_read"]
    assert ub["dist_parent_written"] == 2 * pb["dist_parent_written"]
    vr = eng.relay_graph.vr
    assert pb == state_update_bytes(vr, True)
    # parity of the packed engine the ledger just profiled
    r = eng.run(0)
    d_o, p_o = canonical_bfs(g, 0)
    np.testing.assert_array_equal(r.dist, d_o)
    np.testing.assert_array_equal(r.parent, p_o)


@needs_native
def test_multi_tree_device_extraction_matches_host():
    """multi_tree_to_original_device (the elem-mode verification path)
    agrees with the host extraction tree-for-tree."""
    import jax

    from bfs_tpu.models.bfs import RelayEngine

    g = rmat_graph(8, 8, seed=9)
    eng = RelayEngine(g, sparse_hybrid=False)
    sources = (np.arange(32, dtype=np.int32) * 5) % g.num_vertices
    state = eng.run_multi_elem_device(sources)
    mr = eng.run_multi_elem(sources)
    for i in (0, 7, 31):
        dist_d, parent_d = eng.multi_tree_to_original_device(
            state, i, int(sources[i])
        )
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(dist_d)), mr.dist[i]
        )
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(parent_d)), mr.parent[i]
        )
