"""Sharded relay engine: per-shard Beneš layouts on the mesh vs the oracle.

The TPU-fast gather-free formulation, multi-chip: one unified SPMD program
(shared class structure / network sizes), per-device mask data, frontier
exchanged as the bit-packed all-gather whose block layout each shard's
vperm network absorbs.  Distances AND parents asserted bit-exact against
the canonical oracle at shard counts 1/2/8 — the reference's "N workers,
one machine" methodology (BfsSpark.java:66-108, paper §1.5) on the relay
layout."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import INF_DIST
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import bfs
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

pytestmark = pytest.mark.skipif(
    not __import__("bfs_tpu.graph.benes", fromlist=["native_available"]).native_available(),
    reason="native benes router unavailable",
)


def assert_oracle(g, res, s):
    d, _ = queue_bfs(g, s)
    _, p = canonical_bfs(g, s)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert check(g, res.dist, res.parent, s) == []


@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_relay_sharded_rmat_skewed(num_shards):
    """R-MAT hubs whose in-neighbours span many shards; degree-class
    unification across shards with very different local degree mixes."""
    g = rmat_graph(9, 8, seed=11)
    mesh = make_mesh(graph=num_shards)
    res = bfs_sharded(g, 0, mesh=mesh, engine="relay")
    assert_oracle(g, res, 0)


def test_relay_sharded_deep_graph():
    g = path_graph(257)
    mesh = make_mesh(graph=8)
    res = bfs_sharded(g, 0, mesh=mesh, engine="relay")
    d, p = queue_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert res.num_levels == 257


def test_relay_sharded_disconnected_and_nonzero_source():
    g = gnm_graph(200, 220, seed=3)
    mesh = make_mesh(graph=4)
    res = bfs_sharded(g, 137, mesh=mesh, engine="relay")
    assert_oracle(g, res, 137)
    assert (res.dist == INF_DIST).any()


def test_relay_sharded_matches_pull_sharded_exactly():
    g = rmat_graph(8, 8, seed=21)
    mesh = make_mesh(graph=8)
    relay = bfs_sharded(g, 0, mesh=mesh, engine="relay")
    pull = bfs_sharded(g, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    np.testing.assert_array_equal(relay.dist, pull.dist)
    np.testing.assert_array_equal(relay.parent, pull.parent)
    assert relay.num_levels == pull.num_levels


def test_relay_sharded_single_chip_equivalence():
    """n=1 sharded relay must agree with the single-chip relay engine."""
    g = rmat_graph(9, 6, seed=4)
    mesh = make_mesh(graph=1)
    sharded = bfs_sharded(g, 0, mesh=mesh, engine="relay")
    single = bfs(g, 0, engine="relay")
    np.testing.assert_array_equal(sharded.dist, single.dist)
    np.testing.assert_array_equal(sharded.parent, single.parent)


def test_relay_sharded_prebuilt_layout_reuse():
    from bfs_tpu.graph.relay import build_sharded_relay_graph

    g = rmat_graph(8, 6, seed=2)
    mesh = make_mesh(graph=2)
    srg = build_sharded_relay_graph(g, 2)
    assert srg.num_shards == 2
    for s in [0, 5, 100]:
        res = bfs_sharded(srg, s, mesh=mesh, engine="relay")
        assert_oracle(g, res, s)


def test_relay_sharded_shard_count_mismatch_rejected():
    from bfs_tpu.graph.relay import build_sharded_relay_graph

    g = gnm_graph(64, 128, seed=0)
    srg = build_sharded_relay_graph(g, 2)
    mesh = make_mesh(graph=4)
    with pytest.raises(ValueError):
        bfs_sharded(srg, 0, mesh=mesh, engine="relay")
    with pytest.raises(ValueError):
        bfs_sharded(srg, 0, mesh=make_mesh(graph=2), engine="pull")


def test_relay_sharded_many_sources_small_graph(tiny_graph):
    mesh = make_mesh(graph=2)
    for s in range(6):
        res = bfs_sharded(tiny_graph, s, mesh=mesh, engine="relay")
        assert_oracle(tiny_graph, res, s)
