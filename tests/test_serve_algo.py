"""Registry-resident semiring algorithms (ISSUE 16): serve/algo.py.

Covers: SSSP and CC answered from a :class:`GraphRegistry`'s resident
device operands with oracle-exact results; operand residency reuse (the
second traversal hits the resident entry instead of re-uploading); pull
vs push CC on the same registered graph; pin balance (no pin leaked by
the traversal); and the engine-name guard.
"""

import numpy as np
import pytest

from bfs_tpu.algo import edge_weights_np
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.oracle import dijkstra, union_find_labels
from bfs_tpu.serve import GraphRegistry, registry_cc, registry_sssp

MAXW = 31
SOURCE = 3


@pytest.fixture(scope="module")
def graph():
    return gnm_graph(300, 2100, seed=5)


@pytest.fixture()
def registry(graph):
    reg = GraphRegistry()
    reg.register("g", graph)
    return reg


@pytest.mark.algo_smoke
def test_registry_sssp_oracle_exact(registry, graph):
    w = edge_weights_np(graph.src, graph.dst, MAXW)
    odist, opar = dijkstra(graph, w, SOURCE)
    res = registry_sssp(registry, "g", SOURCE, max_weight=MAXW)
    np.testing.assert_array_equal(res.dist, odist)
    np.testing.assert_array_equal(res.parent, opar)


@pytest.mark.algo_smoke
@pytest.mark.parametrize("engine", ["push", "pull"])
def test_registry_cc_oracle_exact(registry, graph, engine):
    oracle = union_find_labels(graph)
    res = registry_cc(registry, "g", engine=engine)
    assert res.engine == engine
    np.testing.assert_array_equal(res.label, oracle)


def test_registry_operands_stay_resident(registry):
    registry_sssp(registry, "g", SOURCE, max_weight=MAXW)
    assert ("g", 0, "push") in registry.resident_keys()
    first = registry.acquire("g", "push")
    registry_cc(registry, "g")  # rides the SAME resident push operands
    assert registry.acquire("g", "push") is first
    assert registry.resident_keys().count(("g", 0, "push")) == 1


def test_registry_algo_leaves_no_pins(registry):
    registry_sssp(registry, "g", SOURCE, max_weight=MAXW)
    registry_cc(registry, "g", engine="pull")
    assert registry.get("g").pins == 0


def test_registry_cc_rejects_unknown_engine(registry):
    with pytest.raises(ValueError, match="unknown engine"):
        registry_cc(registry, "g", engine="relay")
