"""Chaos-schedule gates for the self-healing serve layer (ISSUE 9).

``tools/chaos_run.py --mode serve`` is the acceptance harness: injected
permanent device faults, hung-call delays, a corrupt on-device answer,
and a mid-load epoch swap, with every reply oracle-checked and every
breaker/watchdog/integrity/epoch transition asserted in the final
metrics snapshot.  The tier-1 smoke here runs a scaled-down schedule
IN-PROCESS (jax is already warm in the test session); the full-size
schedule runs the real CLI in a subprocess and is marked ``slow``.
"""

import os
import subprocess
import sys
import types

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chaos_run():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import chaos_run
    finally:
        sys.path.pop(0)
    return chaos_run


@pytest.mark.chaos
def test_chaos_serve_smoke(monkeypatch):
    """The whole self-healing schedule — breaker open/half-open/close,
    watchdog-degraded hung ticks, integrity quarantine, epoch swap with
    in-flight old-snapshot answers — at tier-1 size.  chaos_serve returns
    non-zero on any wrong answer, frozen tick, or missing transition.

    Runs under ``BFS_TPU_LOCK_ORDER=1`` (ISSUE 12 satellite): every
    serve/registry/executor/health lock acquisition records its ordering
    edges, and the schedule must finish with a CYCLE-FREE lock-order
    graph — the dynamic complement to the LCK001/002 static rules,
    exercised by the most lock-contended path the repo has."""
    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    chaos_run = _chaos_run()
    args = types.SimpleNamespace(
        scale=7,
        edge_factor=4,
        seed=3,
        serve_engine="pull",
        serve_requests=4,
        serve_cooldown_s=0.3,
        serve_delay_s=1.5,
        serve_tick_timeout=120.0,
    )
    import random

    assert chaos_run.chaos_serve(args, random.Random(3)) == 0
    # The schedule restores the fault boundary on every path.
    assert "BFS_TPU_FAULT" not in os.environ
    # The fault+swap schedule nests locks (server tick -> registry
    # acquire, health -> metrics): edges must exist and no interleaving
    # of them may deadlock.
    report = art.lock_order_report()
    assert report["cycles"] == [], report
    assert report["edges"], "no lock nesting recorded — recorder not wired"


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_serve_full_schedule():
    """The real CLI, full-size schedule, fresh process (cold jax, env-var
    fault transport end to end)."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_run.py"),
            "--mode", "serve", "--scale", "9", "--seed", "1",
            "--serve-requests", "12",
        ],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"serve chaos failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout[-4000:]}\nstderr:\n{proc.stderr[-4000:]}"
    )
    assert "serve chaos: ok" in proc.stdout
