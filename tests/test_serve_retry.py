"""Serving-layer retry semantics (ISSUE 3): a TRANSIENT device-path
failure must be retried with backoff — not instantly oracle-degraded, the
pre-resilience behavior — while a PERMANENT failure must degrade to the
sequential oracle exactly once, with the retry counters visible in
``ServeMetrics.report``.  The flaky runner is injected through the real
``ExecutableCache`` seam (``put``), so the whole batch path — coalescing,
cache hit, retry loop, fan-out — is the code under test."""

import numpy as np
import pytest

def _tick_key(graph, engine, padded, epoch=0):
    """The server's executable key carries the direction policy (ISSUE 7)
    and the graph epoch (ISSUE 9) — injected runners must use the same
    key shape."""
    from bfs_tpu.models.direction import resolve_direction

    return (graph, epoch, engine, padded, resolve_direction().key())


from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.oracle.bfs import queue_bfs
from bfs_tpu.resilience.retry import RetryPolicy, TransientError
from bfs_tpu.serve import BfsServer
from bfs_tpu.serve.executor import run_oracle_batch

TIMEOUT = 300


@pytest.fixture
def graph():
    return gnm_graph(60, 150, seed=7)


def make_server(graph, **kw):
    kw.setdefault(
        "retry_policy",
        RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0),
    )
    srv = BfsServer(engine="pull", max_batch=4, **kw)
    srv.register("g", graph)
    return srv


class FlakyRunner:
    """Fails transiently ``fail_n`` times, then serves correct (oracle)
    results forever.  Mimics a device runner whose transport recovers."""

    def __init__(self, graph, fail_n, exc=TransientError("tunnel hiccup")):
        self.graph = graph
        self.fail_n = fail_n
        self.exc = exc
        self.calls = 0

    def __call__(self, sources):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise self.exc
        return run_oracle_batch(self.graph, sources)


def test_transient_failure_is_retried_not_degraded(graph):
    with make_server(graph) as srv:
        flaky = FlakyRunner(graph, fail_n=2)
        # Bucket for one single-source query is 1.
        srv.exe_cache.put(_tick_key("g", "pull", 1), flaky)
        reply = srv.query("g", 5).result(TIMEOUT)

        # Served by the (recovered) device path, not the oracle fallback.
        assert reply.record.status == "ok"
        assert flaky.calls == 3
        d, _ = queue_bfs(graph, 5)
        np.testing.assert_array_equal(reply.dist, d)

        report = srv.report()
        assert report["retries"]["device_retries"] == 2
        assert report["retries"]["device_retry_successes"] == 1
        assert report["retries"]["device_errors"] == 0
        assert report["counters"]["device_retries"] == 2


def test_permanent_failure_degrades_exactly_once(graph):
    with make_server(graph) as srv:
        broken = FlakyRunner(
            graph, fail_n=10**9, exc=ValueError("lowering failed")
        )
        srv.exe_cache.put(_tick_key("g", "pull", 1), broken)
        reply = srv.query("g", 9).result(TIMEOUT)

        # One attempt — permanent errors never burn retries — then the
        # oracle serves the correct answer.
        assert broken.calls == 1
        assert reply.record.status == "oracle"
        d, _ = queue_bfs(graph, 9)
        np.testing.assert_array_equal(reply.dist, d)

        report = srv.report()
        assert report["retries"]["device_retries"] == 0
        assert report["retries"]["device_errors"] == 1


def test_transient_exhaustion_degrades_once_with_counts(graph):
    with make_server(graph) as srv:
        down = FlakyRunner(graph, fail_n=10**9)  # never recovers
        srv.exe_cache.put(_tick_key("g", "pull", 1), down)
        reply = srv.query("g", 3).result(TIMEOUT)

        # max_attempts=3 device tries, then ONE oracle degradation.
        assert down.calls == 3
        assert reply.record.status == "oracle"
        d, _ = queue_bfs(graph, 3)
        np.testing.assert_array_equal(reply.dist, d)

        report = srv.report()
        assert report["retries"]["device_retries"] == 2  # sleeps between tries
        assert report["retries"]["device_retry_successes"] == 0
        assert report["retries"]["device_errors"] == 1


def test_retry_disabled_policy_matches_old_behavior(graph):
    with make_server(
        graph, retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.0)
    ) as srv:
        flaky = FlakyRunner(graph, fail_n=1)  # would recover on 2nd try
        srv.exe_cache.put(_tick_key("g", "pull", 1), flaky)
        reply = srv.query("g", 2).result(TIMEOUT)
        # max_attempts=1 restores degrade-on-first-failure.
        assert flaky.calls == 1
        assert reply.record.status == "oracle"
        d, _ = queue_bfs(graph, 2)
        np.testing.assert_array_equal(reply.dist, d)
