"""Graph registry + device-operand residency tests, including the
drop_device_operands release path (ADVICE.md round-5 finding #1: the hook
was dead code until the serve registry wired it into eviction)."""

import numpy as np
import pytest

from bfs_tpu.graph.ell import (
    build_pull_graph,
    device_ell,
    drop_device_operands,
)
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.serve import GraphRegistry


def test_drop_device_operands_clears_memo_and_reuploads(tiny_graph):
    pg = build_pull_graph(tiny_graph)
    assert getattr(pg, "_device_ell", None) is None
    ell0_a, folds_a = device_ell(pg)
    assert getattr(pg, "_device_ell", None) is not None
    # Memoized: the same call returns the identical device objects.
    ell0_b, _ = device_ell(pg)
    assert ell0_b is ell0_a

    drop_device_operands(pg)
    assert getattr(pg, "_device_ell", None) is None

    # The next call re-uploads: fresh device buffers, same contents.
    ell0_c, folds_c = device_ell(pg)
    assert ell0_c is not ell0_a
    assert getattr(pg, "_device_ell", None) is not None
    np.testing.assert_array_equal(np.asarray(ell0_c), np.asarray(ell0_a))


def test_drop_device_operands_noop_when_not_resident(tiny_graph):
    pg = build_pull_graph(tiny_graph)
    drop_device_operands(pg)  # never uploaded: must not raise
    assert getattr(pg, "_device_ell", None) is None


def test_register_and_layout_memoized(tiny_graph):
    reg = GraphRegistry()
    rec = reg.register("t", tiny_graph)
    assert rec.num_vertices == 6 and rec.num_edges == 16
    pg1 = reg.layout("t", "pull")
    pg2 = reg.layout("t", "pull")
    assert pg1 is pg2  # host layout built once
    with pytest.raises(ValueError):
        reg.layout("t", "bogus")
    with pytest.raises(KeyError):
        reg.get("unknown")
    # Re-registering an existing name is a HOT SWAP, not an error
    # (ISSUE 9): the new registration is the next epoch.
    rec2 = reg.register("t", tiny_graph)
    assert rec2.epoch == 1 and reg.get("t") is rec2
    assert reg.layout("t", "pull") is not pg1  # new epoch, new layout memo


def test_register_prebuilt_pull_layout(tiny_graph):
    pg = build_pull_graph(tiny_graph)
    reg = GraphRegistry()
    reg.register("t", pg)
    assert reg.layout("t", "pull") is pg
    # Other engines need the host graph, which a layout-only registration
    # does not carry.
    with pytest.raises(ValueError):
        reg.layout("t", "push")


def test_acquire_marks_resident_and_release_drops(tiny_graph):
    reg = GraphRegistry()
    reg.register("t", tiny_graph)
    ell0, folds = reg.acquire("t", "pull")
    assert reg.resident_keys() == [("t", 0, "pull")]
    assert reg.resident_bytes() > 0
    pg = reg.layout("t", "pull")
    assert getattr(pg, "_device_ell", None) is not None
    reg.release("t")
    assert reg.resident_keys() == []
    assert getattr(pg, "_device_ell", None) is None
    assert reg.evictions == 1


def test_lru_eviction_under_capped_budget():
    g1 = gnm_graph(200, 500, seed=1)
    g2 = gnm_graph(200, 500, seed=2)
    reg = GraphRegistry(device_budget_bytes=1)  # fits exactly one entry
    reg.register("a", g1)
    reg.register("b", g2)

    reg.acquire("a", "pull")
    pg_a = reg.layout("a", "pull")
    assert getattr(pg_a, "_device_ell", None) is not None

    # Second graph displaces the first: drop_device_operands clears the
    # memo on A's layout (asserted on the object, not log lines).
    reg.acquire("b", "pull")
    assert reg.resident_keys() == [("b", 0, "pull")]
    assert getattr(pg_a, "_device_ell", None) is None
    assert reg.evictions == 1

    # Re-acquiring A re-uploads and displaces B in turn (LRU order).
    ell0_a2, _ = reg.acquire("a", "pull")
    assert reg.resident_keys() == [("a", 0, "pull")]
    assert getattr(pg_a, "_device_ell", None) is not None
    assert reg.evictions == 2


def test_lru_order_tracks_use():
    g1 = gnm_graph(100, 250, seed=3)
    g2 = gnm_graph(100, 250, seed=4)
    g3 = gnm_graph(100, 250, seed=5)
    reg = GraphRegistry(device_budget_bytes=None)
    for n, g in (("a", g1), ("b", g2), ("c", g3)):
        reg.register(n, g)
        reg.acquire(n, "pull")
    # Touch A so B becomes LRU, then cap the budget at exactly-full: the
    # next acquire must evict in LRU order, so B's pull entry goes first
    # and the just-touched A survives.
    reg.acquire("a", "pull")
    reg.device_budget_bytes = reg.resident_bytes()  # full: next evicts
    reg.acquire("b", "push")
    assert ("b", 0, "pull") not in reg.resident_keys()
    assert ("a", 0, "pull") in reg.resident_keys()
    assert ("b", 0, "push") in reg.resident_keys()


def test_second_registry_hits_disk_cache(tmp_path, tiny_graph, monkeypatch):
    """ISSUE 2: a second process-level registration of the same graph must
    load the finished layout from the persistent bundle store instead of
    rebuilding (simulated here with two registry instances sharing a cache
    dir, the second with the builder poisoned)."""
    from bfs_tpu.utils.metrics import ServeMetrics

    cache_dir = str(tmp_path / "layout")
    m1 = ServeMetrics()
    reg1 = GraphRegistry(layout_cache=cache_dir, metrics=m1)
    reg1.register("g", tiny_graph)
    pg1 = reg1.layout("g", "pull")
    assert m1.count("layout_disk_misses") == 1

    # "Second process": fresh registry, same disk cache; if it tried to
    # rebuild, the poisoned builder would raise.
    import bfs_tpu.graph.ell as ell_mod

    def poisoned(*a, **k):
        raise AssertionError("layout was rebuilt despite a warm disk cache")

    monkeypatch.setattr(ell_mod, "build_pull_graph", poisoned)
    m2 = ServeMetrics()
    reg2 = GraphRegistry(layout_cache=cache_dir, metrics=m2)
    reg2.register("g", tiny_graph)
    pg2 = reg2.layout("g", "pull")
    assert m2.count("layout_disk_hits") == 1
    np.testing.assert_array_equal(np.asarray(pg2.ell0), np.asarray(pg1.ell0))
    # The serve report surfaces the process-global artifact counters.
    assert m2.report()["artifact_caches"]["layout_cache_hits"] >= 1


def test_registry_without_cache_never_touches_disk(tiny_graph, tmp_path):
    reg = GraphRegistry()  # layout_cache=None: in-process memoization only
    assert reg.layout_cache is None
    reg.register("g", tiny_graph)
    reg.layout("g", "pull")


def test_unregister_evicts(tiny_graph):
    reg = GraphRegistry()
    reg.register("t", tiny_graph)
    reg.acquire("t", "pull")
    pg = reg.layout("t", "pull")
    reg.unregister("t")
    assert reg.resident_keys() == []
    assert getattr(pg, "_device_ell", None) is None
    with pytest.raises(KeyError):
        reg.get("t")
