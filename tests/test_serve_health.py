"""Self-healing serve tests (ISSUE 9): circuit-breaker state machine,
hung-call watchdog, sampled on-device integrity checks, epoch-versioned
hot graph swaps — unit level (fake clocks, injected runners through the
real ``ExecutableCache`` seam) plus server-level integration where the
whole tick path (coalesce → breaker gate → watchdog → verify → fan-out)
is the code under test.  Every served reply is still oracle-checked:
self-healing must never change an answer, only where it was computed."""

import threading
import time

import numpy as np
import pytest

from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.oracle.bfs import queue_bfs
from bfs_tpu.resilience.retry import (
    CircuitBreaker,
    PermanentError,
    RetryPolicy,
)
from bfs_tpu.serve import BfsServer, GraphRegistry, HungCallError
from bfs_tpu.serve.executor import run_oracle_batch
from bfs_tpu.serve.health import ServeHealth, run_with_deadline

TIMEOUT = 300


def _tick_key(graph, engine, padded, epoch=0):
    from bfs_tpu.models.direction import resolve_direction

    return (graph, epoch, engine, padded, resolve_direction().key())


@pytest.fixture
def graph():
    return gnm_graph(60, 150, seed=7)


def make_server(graph, **kw):
    kw.setdefault(
        "retry_policy", RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0)
    )
    srv = BfsServer(engine="pull", max_batch=4, **kw)
    srv.register("g", graph)
    return srv


# ------------------------------------------------------------------ breaker --


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_threshold_and_cools_down():
    clock = FakeClock()
    transitions = []
    br = CircuitBreaker(
        failure_threshold=3, cooldown_s=10.0, clock=clock,
        on_transition=lambda k, old, new, why: transitions.append((k, old, new)),
    )
    key = ("g", 0, "pull", 4)
    assert br.allow(key) and br.state(key) == "closed"
    br.record_failure(key)
    br.record_failure(key)
    assert br.allow(key)  # two strikes: still closed
    br.record_failure(key)
    assert br.state(key) == "open"
    assert not br.allow(key)  # short-circuit during cooldown
    clock.t += 9.9
    assert not br.allow(key)
    clock.t += 0.2  # cooldown elapsed: next allow is the canary
    assert br.state(key) == "half_open"
    assert br.allow(key)
    assert not br.allow(key)  # exactly ONE canary per probe window
    br.record_success(key)
    assert br.state(key) == "closed" and br.allow(key)
    assert [(old, new) for _, old, new in transitions] == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "closed"),
    ]


def test_breaker_canary_failure_reopens():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
    br.record_failure("k", "boom")
    clock.t += 5.1
    assert br.allow("k")  # canary admitted
    br.record_failure("k", "still broken")
    assert br.state("k") == "open"
    assert not br.allow("k")  # a FRESH cooldown from the canary failure
    clock.t += 5.1
    assert br.allow("k")
    br.record_success("k")
    assert br.state("k") == "closed"


def test_breaker_force_open_is_immediate_quarantine():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=99, cooldown_s=5.0, clock=clock)
    assert br.allow("k")
    br.force_open("k", "integrity verdict {'dist_gap': 1}")
    assert br.state("k") == "open" and not br.allow("k")
    snap = br.snapshot()
    assert snap["k"]["state"] == "open"
    assert "integrity" in snap["k"]["reason"]


def test_breaker_forget_drops_matching_circuits():
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=FakeClock())
    br.record_failure(("g", 0, "pull", 4))
    br.record_failure(("g", 1, "pull", 4))
    assert br.forget(lambda k: k[1] == 0) == 1
    snap = br.snapshot()
    assert "g/0/pull/4" not in snap and "g/1/pull/4" in snap
    # A forgotten circuit restarts closed if the key ever comes back.
    assert br.allow(("g", 0, "pull", 4))


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker(failure_threshold=2, cooldown_s=5.0, clock=FakeClock())
    br.record_failure("k")
    br.record_success("k")
    br.record_failure("k")
    assert br.state("k") == "closed"  # never two CONSECUTIVE failures


def test_breaker_is_thread_safe_under_concurrent_hammering():
    br = CircuitBreaker(failure_threshold=3, cooldown_s=0.0)
    errs = []

    def worker():
        try:
            for _ in range(200):
                if br.allow("k"):
                    br.record_failure("k")
                else:
                    br.record_success("k")
        except Exception as exc:  # pragma: no cover
            errs.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert br.state("k") in ("closed", "open", "half_open")


# ----------------------------------------------------------------- watchdog --


def test_run_with_deadline_returns_value_and_propagates_errors():
    assert run_with_deadline(lambda: 42, 5.0) == 42
    with pytest.raises(ZeroDivisionError):
        run_with_deadline(lambda: 1 / 0, 5.0)


def test_run_with_deadline_times_out_a_wedged_call():
    t0 = time.monotonic()
    with pytest.raises(HungCallError):
        run_with_deadline(lambda: time.sleep(5.0), 0.1, describe="wedge")
    assert time.monotonic() - t0 < 2.0  # returned at the deadline, not 5 s


def test_watchdog_budget_is_default_then_p99_informed():
    from bfs_tpu.utils.metrics import ServeMetrics

    h = ServeHealth(metrics=ServeMetrics(), watchdog_s=30.0,
                    watchdog_multiplier=4.0, watchdog_min_s=0.5)
    key = ("g", 0, "pull", 4)
    assert h.budget_s(key) == 30.0  # no history: the configured default
    for _ in range(ServeHealth.MIN_SAMPLES):
        h.observe_latency(key, 0.01)
    # multiplier x p99 = 0.04 floors at watchdog_min_s
    assert h.budget_s(key) == 0.5
    for _ in range(ServeHealth.MIN_SAMPLES):
        h.observe_latency(key, 1.0)
    assert h.budget_s(key) == pytest.approx(4.0)


def test_watchdog_timeout_tightens_to_earliest_request_deadline():
    from bfs_tpu.utils.metrics import ServeMetrics

    h = ServeHealth(metrics=ServeMetrics(), watchdog_s=30.0, watchdog_min_s=0.5)
    key = ("g", 0, "pull", 4)
    now = time.monotonic()
    # Earliest deadline 2 s out: timeout = remaining + grace, not 30 s.
    t = h.timeout_for(key, [now + 2.0, now + 50.0], now=now)
    assert t == pytest.approx(2.5, abs=0.01)
    # Expired deadline: only the grace remains (never below min).
    assert h.timeout_for(key, [now - 1.0], now=now) == 0.5
    # Disabled watchdog: no timeout at all.
    h2 = ServeHealth(metrics=ServeMetrics(), watchdog_s=0.0)
    assert h2.timeout_for(key, [now + 2.0], now=now) is None


def test_cold_tick_latency_stays_out_of_the_budget_window():
    """A cold call's duration includes the AOT build; feeding it into the
    p99 window would inflate the warm watchdog budget by ~multiplier ×
    compile time for the next ~window of ticks."""
    from bfs_tpu.utils.metrics import ServeMetrics

    h = ServeHealth(metrics=ServeMetrics(), watchdog_s=5.0)
    key = ("g", 0, "pull", 4)
    h.run_guarded(key, lambda: time.sleep(0.05), [], cold=True)
    assert h.report()["watchdog_budgets"] == {}
    h.run_guarded(key, lambda: None, [], cold=False)
    assert h.report()["watchdog_budgets"]["g/0/pull/4"]["samples"] == 1


def test_hung_integrity_check_degrades_instead_of_freezing(
    graph, monkeypatch
):
    """The sampled verify is device work on the serve thread: a wedge
    inside the checker must land as check-couldn't-run under the
    watchdog, not block every queue on every graph forever."""
    from bfs_tpu.oracle.device import DeviceChecker

    def wedged_check(self, *a, **kw):
        time.sleep(30.0)
        return {}

    monkeypatch.setattr(DeviceChecker, "check", wedged_check)
    # Budget sizing, tuned for a deep-in-the-suite run on the 2-core
    # container: the cold BATCH call (AOT build included) is floored at
    # compile_floor_s and must never be false-positived into 'oracle' —
    # late in a long pytest process a cold serve compile was measured
    # over the old 1.2 s floor, which flipped the 'ok' assertion.  The
    # wedge (30 s) dwarfs every budget, so the verify kill at the floor
    # (~3 s; the checker is cold on its first sample) still proves the
    # loop cannot freeze.
    with make_server(
        graph, verify_sample=1, watchdog_s=1.0,
        watchdog_compile_floor_s=3.0,
    ) as srv:
        t0 = time.monotonic()
        reply = srv.query("g", 0).result(TIMEOUT)
        assert time.monotonic() - t0 < 10.0, "serve loop froze in verify"
        assert reply.record.status == "ok"  # the batch itself was fine
        assert srv.metrics.count("integrity_check_errors") == 1
        assert srv.metrics.count("integrity_failures") == 0


def test_run_guarded_cold_floor_admits_an_honest_compile():
    from bfs_tpu.utils.metrics import ServeMetrics

    h = ServeHealth(
        metrics=ServeMetrics(), watchdog_s=0.05, watchdog_min_s=0.01,
        compile_floor_s=0.5,
    )
    key = ("g", 0, "pull", 4)
    deadlines = [time.monotonic() + 0.02]
    # Warm budget (0.05 s, deadline-tightened lower still) would kill a
    # 0.15 s call...
    with pytest.raises(HungCallError):
        h.run_guarded(key, lambda: time.sleep(0.15) or "x", deadlines)
    # ...but a COLD call (executable build included) is floored at
    # compile_floor_s — an honest compile is never false-positived, and
    # request deadlines do not tighten below the floor.
    assert (
        h.run_guarded(key, lambda: time.sleep(0.15) or "x", deadlines,
                      cold=True)
        == "x"
    )
    # A wedged compile still times out: the floor is finite.
    with pytest.raises(HungCallError):
        h.run_guarded(key, lambda: time.sleep(5.0), [], cold=True)


def test_checker_cache_keeps_one_epoch_per_name():
    """Each DeviceChecker pins its own device copy of the edge arrays
    OUTSIDE the registry budget — inserting a current epoch's checker
    must drop the same graph's other epochs."""
    import types

    from bfs_tpu.utils.metrics import ServeMetrics

    g = gnm_graph(40, 90, seed=11)
    h = ServeHealth(metrics=ServeMetrics(), verify_sample=1)
    rec0 = types.SimpleNamespace(name="g", epoch=0, graph=g, retired=False)
    h._checker(rec0)
    assert list(h._checkers) == [("g", 0)]
    rec1 = types.SimpleNamespace(name="g", epoch=1, graph=g, retired=False)
    h._checker(rec1)
    assert list(h._checkers) == [("g", 1)]
    # A RETIRED epoch's checker (an in-flight batch straddling the swap)
    # is transient: cached without evicting the current epoch's.
    rec0.retired = True
    h._checker(rec0)
    assert set(h._checkers) == {("g", 0), ("g", 1)}


# ------------------------------------------- server integration: breaker --


class FailNThenGood:
    """Raises PermanentError for the first ``fail_n`` calls, then serves
    correct oracle results — the recovering-executable shape."""

    def __init__(self, graph, fail_n):
        self.graph = graph
        self.fail_n = fail_n
        self.calls = 0

    def __call__(self, sources):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise PermanentError(f"poisoned executable (call {self.calls})")
        return run_oracle_batch(self.graph, sources)


def test_breaker_opens_short_circuits_then_canary_closes(graph):
    with make_server(
        graph, breaker_failures=2, breaker_cooldown_s=0.15, watchdog_s=0.0
    ) as srv:
        srv.exe_cache.put(_tick_key("g", "pull", 1), FailNThenGood(graph, 2))
        # Two permanently failing ticks: each degrades to the oracle
        # (correct answers) and strikes the circuit.  Distinct sources —
        # a repeat would hit the result LRU and never reach the device.
        for s in (0, 1):
            reply = srv.query("g", s).result(TIMEOUT)
            ds, _ = queue_bfs(graph, s)
            assert reply.record.status == "oracle"
            assert np.array_equal(reply.dist, ds)
        assert srv.metrics.count("breaker_opened") == 1
        # Circuit open: the next tick must short-circuit (no device call).
        reply = srv.query("g", 2).result(TIMEOUT)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("breaker_short_circuits") >= 1
        # After the cooldown the canary tick goes back to the device path
        # (the runner recovered) and the circuit closes.
        time.sleep(0.2)
        reply = srv.query("g", 3).result(TIMEOUT)
        d3, _ = queue_bfs(graph, 3)
        assert np.array_equal(reply.dist, d3)
        assert reply.record.status == "ok"
        assert srv.metrics.count("breaker_half_open") == 1
        assert srv.metrics.count("breaker_closed") == 1
        # Steady state again: device path, circuit closed.
        reply = srv.query("g", 4).result(TIMEOUT)
        assert reply.record.status == "ok"
        snap = srv.report()["health"]["breaker"]
        assert all(c["state"] == "closed" for c in snap.values())


def test_transient_flakes_do_not_trip_the_breaker(graph):
    from bfs_tpu.resilience.retry import TransientError

    class Flaky:
        def __init__(self):
            self.calls = 0

        def __call__(self, sources):
            self.calls += 1
            if self.calls % 2:
                raise TransientError("tunnel hiccup")
            return run_oracle_batch(graph, sources)

    with make_server(graph, breaker_failures=1, watchdog_s=0.0) as srv:
        srv.exe_cache.put(_tick_key("g", "pull", 1), Flaky())
        for s in range(4):
            reply = srv.query("g", s).result(TIMEOUT)
            ds, _ = queue_bfs(graph, s)
            assert np.array_equal(reply.dist, ds)
        # Every tick flaked once and recovered within its retry loop: the
        # breaker (threshold ONE) must never have opened.
        assert srv.metrics.count("breaker_opened") == 0
        assert srv.metrics.count("device_retries") >= 4


# ------------------------------------------ server integration: watchdog --


def test_hung_call_times_out_degrades_and_strikes_breaker(graph):
    class Wedged:
        def __init__(self):
            self.calls = 0

        def __call__(self, sources):
            self.calls += 1
            if self.calls == 1:
                time.sleep(5.0)  # a wedged XLA dispatch
            return run_oracle_batch(graph, sources)

    with make_server(
        graph, breaker_failures=2, watchdog_s=0.3, watchdog_min_s=0.05
    ) as srv:
        srv.exe_cache.put(_tick_key("g", "pull", 1), Wedged())
        t0 = time.monotonic()
        reply = srv.query("g", 0).result(TIMEOUT)
        # The tick degraded to the oracle instead of freezing the server,
        # and it did so around the watchdog budget, not the 5 s sleep.
        assert time.monotonic() - t0 < 4.0
        d0, _ = queue_bfs(graph, 0)
        assert np.array_equal(reply.dist, d0)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("watchdog_timeouts") == 1
        # HungCallError is permanent: one breaker strike, no retry burn.
        assert srv.metrics.count("device_retries") == 0
        # The next tick is healthy (the wedge was one call).
        reply = srv.query("g", 1).result(TIMEOUT)
        assert reply.record.status == "ok"
        assert srv.metrics.count("breaker_opened") == 0


def test_injected_delay_fault_trips_watchdog(graph, monkeypatch):
    """``BFS_TPU_FAULT=delay:serve.batch:5`` wedges the REAL device batch
    call (no mock runner): the watchdog must catch it and the tick must
    degrade with a correct answer."""
    with make_server(graph, watchdog_s=0.3, watchdog_min_s=0.05) as srv:
        srv.query("g", 5).result(TIMEOUT)  # compile outside the fault window
        monkeypatch.setenv("BFS_TPU_FAULT", "delay:serve.batch:5")
        t0 = time.monotonic()
        reply = srv.query("g", 0).result(TIMEOUT)
        assert time.monotonic() - t0 < 4.0
        monkeypatch.delenv("BFS_TPU_FAULT")
        d0, _ = queue_bfs(graph, 0)
        assert np.array_equal(reply.dist, d0)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("watchdog_timeouts") == 1


def test_hung_build_times_out_instead_of_freezing_the_server(
    graph, monkeypatch
):
    """The executable BUILD runs under the watchdog too (cold ticks get
    the compile_floor_s budget): a wedged lower/compile must degrade the
    tick like a wedged dispatch, not block the serve loop forever."""
    import bfs_tpu.serve.server as server_mod

    def wedged_build(*a, **kw):
        time.sleep(5.0)
        raise AssertionError("unreachable: the watchdog fires first")

    monkeypatch.setattr(server_mod, "build_batch_runner", wedged_build)
    with make_server(
        graph, watchdog_s=0.2, watchdog_min_s=0.05,
        watchdog_compile_floor_s=0.4,
    ) as srv:
        t0 = time.monotonic()
        reply = srv.query("g", 0).result(TIMEOUT)
        # Degraded around the 0.4 s cold floor, not the 5 s wedge.
        assert time.monotonic() - t0 < 4.0
        d0, _ = queue_bfs(graph, 0)
        assert np.array_equal(reply.dist, d0)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("watchdog_timeouts") == 1


# ----------------------------------------- server integration: integrity --


def test_sampled_integrity_check_passes_on_healthy_path(graph):
    with make_server(graph, verify_sample=1, watchdog_s=0.0) as srv:
        for s in range(3):
            reply = srv.query("g", s).result(TIMEOUT)
            assert reply.record.status == "ok"
        assert srv.metrics.count("integrity_checks") == 3
        assert srv.metrics.count("integrity_failures") == 0


def test_integrity_failure_quarantines_and_reruns_on_fallback(
    graph, monkeypatch
):
    with make_server(
        graph, verify_sample=1, breaker_cooldown_s=0.15, watchdog_s=0.0
    ) as srv:
        reply = srv.query("g", 0).result(TIMEOUT)
        assert reply.record.status == "ok"
        n_exe = len(srv.exe_cache)
        # Injected corruption: the next sampled verify FAILS its verdict.
        monkeypatch.setenv("BFS_TPU_FAULT", "raise:serve.verify")
        reply = srv.query("g", 1).result(TIMEOUT)
        monkeypatch.delenv("BFS_TPU_FAULT")
        # The batch re-ran on the fallback path and the answer is correct.
        d1, _ = queue_bfs(graph, 1)
        assert np.array_equal(reply.dist, d1)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("integrity_failures") == 1
        # Quarantine: circuit force-opened AND the cached runner dropped.
        assert srv.metrics.count("breaker_opened") == 1
        assert len(srv.exe_cache) == n_exe - 1
        # While quarantined, ticks short-circuit (still correct).
        reply = srv.query("g", 2).result(TIMEOUT)
        assert reply.record.status == "oracle"
        # After the cooldown the canary REBUILDS the executable (a compile
        # miss, not a re-probe of the quarantined artifact) and closes.
        time.sleep(0.2)
        reply = srv.query("g", 3).result(TIMEOUT)
        assert reply.record.status == "ok"
        assert srv.metrics.count("breaker_closed") == 1
        assert len(srv.exe_cache) == n_exe


# --------------------------------------------------------------- epochs --


def test_hot_swap_creates_epoch_and_in_flight_finishes_on_old(graph):
    """The acceptance shape: queries admitted before a swap are answered
    against the snapshot they were admitted under; queries admitted after
    see the new graph."""
    other = gnm_graph(60, 180, seed=8)  # same V, different edges
    with make_server(graph, watchdog_s=0.0) as srv:
        srv.query("g", 0).result(TIMEOUT)  # warm epoch 0
        srv.pause()
        # Admitted under epoch 0, still queued when the swap lands.
        f_old = [srv.submit("g", [s]) for s in (3, 4)]
        srv.register("g", other)  # hot swap -> epoch 1
        f_new = [srv.submit("g", [s]) for s in (3, 4)]
        srv.resume()
        for s, f in zip((3, 4), f_old):
            reply = f.result(TIMEOUT)
            d, _ = queue_bfs(graph, s)
            assert reply.record.epoch == 0
            assert np.array_equal(reply.dist, d), "old-epoch answer wrong"
        for s, f in zip((3, 4), f_new):
            reply = f.result(TIMEOUT)
            d, _ = queue_bfs(other, s)
            assert reply.record.epoch == 1
            assert np.array_equal(reply.dist, d), "new-epoch answer wrong"
        assert srv.metrics.count("epochs_swapped") == 1
        # The old epoch retired once its last in-flight pin dropped.
        assert srv.metrics.count("epochs_retired") == 1
        with pytest.raises(KeyError):
            srv.registry.get_epoch("g", 0)
        assert srv.registry.epoch("g") == 1


def test_result_cache_is_epoch_keyed(graph):
    other = gnm_graph(60, 180, seed=8)
    with make_server(graph, watchdog_s=0.0) as srv:
        srv.query("g", 0).result(TIMEOUT)
        srv.query("g", 0).result(TIMEOUT)
        assert srv.metrics.count("result_cache_hits") == 1
        srv.register("g", other)
        # Same source, new epoch: the old cached answer must NOT serve.
        reply = srv.query("g", 0).result(TIMEOUT)
        d, _ = queue_bfs(other, 0)
        assert np.array_equal(reply.dist, d)
        assert srv.metrics.count("result_cache_hits") == 1


def test_swap_with_no_inflight_retires_old_epoch_immediately(graph):
    reg = GraphRegistry()
    reg.register("g", graph)
    reg.acquire("g", "pull")
    assert reg.resident_keys() == [("g", 0, "pull")]
    reg.register("g", graph)
    # No pins: epoch 0's operands were evicted at swap time.
    assert reg.resident_keys() == []
    assert reg.epoch("g") == 1
    with pytest.raises(KeyError):
        reg.get_epoch("g", 0)


def test_pinned_epoch_survives_swap_until_unpin(graph):
    reg = GraphRegistry()
    reg.register("g", graph)
    rec0 = reg.pin("g")
    reg.acquire("g", "pull")
    reg.register("g", graph)
    # Pinned: epoch 0 and its operands stay alive through the swap.
    assert reg.get_epoch("g", 0) is rec0
    assert ("g", 0, "pull") in reg.resident_keys()
    reg.unpin(rec0)
    assert reg.resident_keys() == []
    with pytest.raises(KeyError):
        reg.get_epoch("g", 0)


def test_epochs_are_monotonic_across_unregister(graph):
    """An unregister/re-register cycle must NOT restart epoch numbering:
    an in-flight query pinned to the old incarnation's epoch N would
    silently resolve against a new graph that reused N and be answered
    from the wrong snapshot."""
    reg = GraphRegistry()
    reg.register("g", graph)
    rec0 = reg.pin("g")
    assert rec0.epoch == 0
    reg.unregister("g")
    other = gnm_graph(60, 180, seed=21)
    assert reg.register("g", other).epoch == 1
    # The old incarnation's epoch is GONE, not aliased to the new graph.
    with pytest.raises(KeyError):
        reg.get_epoch("g", 0)


def test_late_unpin_after_unregister_releases_exactly_once(graph):
    """unregister force-drops a still-pinned retired epoch; the eventual
    unpin must be a no-op — not a second _retire that re-fires listeners
    and sweeps a re-registered incarnation's live residents."""
    retired = []
    reg = GraphRegistry()
    reg.add_retire_listener(lambda n, e: retired.append((n, e)))
    reg.register("g", graph)
    rec0 = reg.pin("g")
    reg.register("g", graph)  # swap; epoch 0 retired-but-pinned
    reg.unregister("g")  # force-drop: fires for epochs 1 (current) and 0
    assert sorted(retired) == [("g", 0), ("g", 1)]
    reg.register("g", graph)  # new incarnation, epoch 2
    reg.acquire("g", "pull")
    assert ("g", 2, "pull") in reg.resident_keys()
    reg.unpin(rec0)  # the in-flight work from before the unregister ends
    assert sorted(retired) == [("g", 0), ("g", 1)], "released twice"
    assert ("g", 2, "pull") in reg.resident_keys(), (
        "late unpin swept the live incarnation's residency"
    )


def test_retire_listeners_fan_out_and_detach(graph):
    """Multiple servers share one registry: each subscribes its own
    listener (a slot would let the second server steal the hook) and a
    removed listener stops firing."""
    a, b = [], []
    fa, fb = (lambda n, e: a.append(e)), (lambda n, e: b.append(e))
    reg = GraphRegistry()
    reg.add_retire_listener(fa)
    reg.add_retire_listener(fb)
    reg.register("g", graph)
    reg.register("g", graph)  # swap retires epoch 0 -> both fire
    assert a == [0] and b == [0]
    reg.remove_retire_listener(fa)
    reg.register("g", graph)  # retires epoch 1 -> only b fires
    assert a == [0] and b == [0, 1]


def test_retired_epoch_upload_race_does_not_leak_residency(graph):
    """A watchdog-abandoned worker can finish acquire_for's out-of-lock
    H2D upload AFTER the epoch's last unpin ran _retire: the late insert
    must be refused, or the dead snapshot's device arrays stay resident
    forever (with the default unlimited budget, _make_room never evicts)."""
    reg = GraphRegistry()
    reg.register("g", graph)
    rec0 = reg.pin("g")
    reg.register("g", graph)  # swap; epoch 0 retired-but-pinned
    reg.unpin(rec0)  # last pin drops -> _retire evicts epoch 0
    assert reg.resident_keys() == []
    # The abandoned worker's upload completes now.
    operands = reg.acquire_for(rec0, "pull")
    assert operands is not None  # the (dead) caller still gets operands
    assert ("g", 0, "pull") not in reg.resident_keys(), (
        "retired epoch re-inserted into residency after _retire"
    )


def test_epoch_retirement_prunes_health_state(graph):
    """Per-circuit breaker cells and latency windows are keyed by epoch;
    retirement must prune them or every hot swap grows health state (and
    the report payload) for the server's lifetime."""
    with make_server(graph, watchdog_s=0.0) as srv:
        srv.query("g", 0).result(TIMEOUT)  # cold tick: builds, no sample
        srv.query("g", 1).result(TIMEOUT)  # warm tick: feeds the window
        rep = srv.report()["health"]
        assert any(k.split("/")[1] == "0" for k in rep["watchdog_budgets"])
        srv.register("g", graph)  # hot swap, nothing in flight
        srv.query("g", 2).result(TIMEOUT)
        srv.query("g", 3).result(TIMEOUT)
        rep = srv.report()["health"]
        for section in (rep["watchdog_budgets"], rep["breaker"]):
            assert not any(k.split("/")[1] == "0" for k in section), (
                f"epoch-0 health state survived retirement: {section}"
            )
        assert any(k.split("/")[1] == "1" for k in rep["watchdog_budgets"])


def test_report_tolerates_concurrent_unregister(graph, monkeypatch):
    """names() and epoch() are separate lock acquisitions: a graph
    unregistered between them must drop out of the snapshot, not raise
    KeyError at the monitoring caller."""
    with make_server(graph, watchdog_s=0.0) as srv:
        real_names = srv.registry.names
        monkeypatch.setattr(
            srv.registry, "names", lambda: real_names() + ["gone"]
        )
        rep = srv.report()
        assert rep["registry"]["graphs"] == ["g"]
        assert rep["registry"]["epochs"] == {"g": 0}


def test_unpinned_swap_releases_device_operands_of_old_layout(graph):
    """Swap-time retirement must run the same release hooks as the
    last-unpin path: the old rec is already out of _graphs when _retire
    runs, so _evict needs the rec handed to it — otherwise an
    externally-held pull layout keeps its device memo (multi-GB at
    scale) alive after the swap."""
    reg = GraphRegistry()
    reg.register("g", graph)
    reg.acquire("g", "pull")
    pg = reg.layout("g", "pull")
    assert getattr(pg, "_device_ell", None) is not None
    reg.register("g", graph)  # unpinned swap retires epoch 0 immediately
    assert reg.resident_keys() == []
    assert getattr(pg, "_device_ell", None) is None, (
        "swap-time _retire skipped drop_device_operands"
    )


def test_budget_eviction_happens_before_the_new_upload(graph, monkeypatch):
    """Victims must leave the device BEFORE the incoming operands are
    uploaded, or peak HBM is budget + incoming — the overshoot the
    budget exists to prevent."""
    import bfs_tpu.serve.registry as registry_mod

    other = gnm_graph(60, 150, seed=9)
    reg = GraphRegistry(device_budget_bytes=1)
    reg.register("a", graph)
    reg.register("b", other)
    reg.acquire("a", "pull")
    assert ("a", 0, "pull") in reg.resident_keys()
    resident_at_upload = []
    real_device_ell = registry_mod.device_ell

    def spying_device_ell(layout):
        resident_at_upload.append(list(reg.resident_keys()))
        return real_device_ell(layout)

    monkeypatch.setattr(registry_mod, "device_ell", spying_device_ell)
    reg.acquire("b", "pull")
    assert resident_at_upload == [[]], (
        "victim still resident while the new operands uploaded"
    )


def test_budget_eviction_defers_on_pinned_epochs(graph):
    other = gnm_graph(60, 150, seed=9)
    reg = GraphRegistry(device_budget_bytes=1)
    reg.register("a", graph)
    reg.register("b", other)
    rec_a = reg.pin("a")
    reg.acquire("a", "pull")
    # b's acquire would evict a (LRU), but a is pinned by in-flight work:
    # the eviction is DEFERRED and both stay resident (budget overshoot).
    reg.acquire("b", "pull")
    assert reg.evictions_deferred == 1
    assert ("a", 0, "pull") in reg.resident_keys()
    assert ("b", 0, "pull") in reg.resident_keys()
    reg.unpin(rec_a)
    # Next acquire settles the budget: a (unpinned now) is evicted.
    reg.acquire("b", "pull")
    assert reg.resident_keys() == [("b", 0, "pull")]
