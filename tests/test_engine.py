"""Differential tests: TPU engine vs sequential oracle (the reference's
verification methodology, SURVEY.md §4: same problem files, outputs must
agree; here automated and bit-exact)."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import Graph, INF_DIST, build_device_graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import SuperstepRunner, bfs
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs


def assert_matches_oracle(graph, result, source=0):
    d, _ = queue_bfs(graph, source)
    np.testing.assert_array_equal(result.dist, d)  # distances: bit-exact
    _, p = canonical_bfs(graph, source)
    np.testing.assert_array_equal(result.parent, p)  # canonical parents
    assert check(graph, result.dist, result.parent, source) == []


def test_tiny_fused(tiny_graph):
    res = bfs(tiny_graph, 0)
    assert res.dist.tolist() == [0, 1, 1, 2, 2, 1]
    assert res.parent.tolist() == [0, 0, 0, 2, 2, 0]
    # 3 supersteps, matching the paper's parallel iteration count
    # (docs/BigData_Project.pdf §1.3).
    assert res.num_levels == 3
    assert res.path_to(3) == [0, 2, 3]
    assert res.dist_to(4) == 2 and res.has_path_to(4)


def test_tiny_from_other_sources(tiny_graph):
    for s in range(6):
        assert_matches_oracle(tiny_graph, bfs(tiny_graph, s), s)


def test_medium(medium_graph):
    assert_matches_oracle(medium_graph, bfs(medium_graph, 0))


def test_random_graphs():
    for seed in range(4):
        g = gnm_graph(300, 700, seed=seed)  # typically disconnected
        assert_matches_oracle(g, bfs(g, 0))


def test_rmat():
    g = rmat_graph(8, 8, seed=3)
    assert_matches_oracle(g, bfs(g, 0))


def test_deep_path_graph():
    g = path_graph(50)  # worst-case diameter: 50 supersteps
    res = bfs(g, 0)
    assert res.dist.tolist() == list(range(50))
    assert_matches_oracle(g, res)


def test_isolated_source():
    g = Graph.from_undirected_edges(4, np.array([[1, 2]]))
    res = bfs(g, 0)
    assert res.dist[0] == 0 and (res.dist[1:] == INF_DIST).all()
    assert res.num_levels == 1  # one superstep that finds nothing


def test_max_levels_cutoff():
    g = path_graph(10)
    res = bfs(g, 0, max_levels=3)
    assert res.dist[3] == 3 and res.dist[4] == INF_DIST


def test_stepped_equals_fused(tiny_graph):
    runner = SuperstepRunner(tiny_graph)
    stepped = runner.run(0)
    fused = bfs(tiny_graph, 0)
    np.testing.assert_array_equal(stepped.dist, fused.dist)
    np.testing.assert_array_equal(stepped.parent, fused.parent)
    assert stepped.num_levels == fused.num_levels


def test_stepped_observer_frontier_sizes(tiny_graph):
    runner = SuperstepRunner(tiny_graph)
    sizes = []
    runner.run(0, observer=lambda lvl, s: sizes.append(runner.frontier_size(s)))
    # Frontiers: {1,2,5} then {3,4} then {} (paper Tables 3-6 progression).
    assert sizes == [3, 2, 0]


@pytest.mark.parametrize("engine", ["pull", "relay"])
def test_stepped_fast_engines(tiny_graph, engine):
    """Observability parity for the TPU-fast layouts: stepped == fused,
    per-superstep frontier sizes visible, dumps in original-id space."""
    if engine == "relay":
        from bfs_tpu.graph.benes import native_available

        if not native_available():
            pytest.skip("native benes router unavailable")
    runner = SuperstepRunner(tiny_graph, engine=engine)
    sizes = []
    stepped = runner.run(0, observer=lambda lvl, s: sizes.append(runner.frontier_size(s)))
    fused = bfs(tiny_graph, 0)
    np.testing.assert_array_equal(stepped.dist, fused.dist)
    np.testing.assert_array_equal(stepped.parent, fused.parent)
    assert stepped.num_levels == fused.num_levels
    assert sizes == [3, 2, 0]  # paper Tables 3-6 progression


@pytest.mark.parametrize("engine", ["pull", "relay"])
def test_stepped_to_original_midrun(engine):
    """to_original maps mid-run state back to original ids (relay relabels)."""
    if engine == "relay":
        from bfs_tpu.graph.benes import native_available

        if not native_available():
            pytest.skip("native benes router unavailable")
    g = rmat_graph(7, 8, seed=5)
    runner = SuperstepRunner(g, engine=engine)
    state = runner.init(0)
    state = runner.step(state)
    dist, parent, frontier = runner.to_original(state, source=0)
    d, _ = queue_bfs(g, 0)
    lvl1 = d == 1
    np.testing.assert_array_equal(dist == 1, lvl1)
    np.testing.assert_array_equal(frontier.astype(bool)[: g.num_vertices], lvl1)
    assert dist[0] == 0 and parent[0] == 0
    final = runner.run(0)
    assert_matches_oracle(g, final, 0)


def test_self_loops_and_multi_edges():
    g = Graph.from_undirected_edges(3, np.array([[0, 0], [0, 1], [0, 1], [1, 2]]))
    assert_matches_oracle(g, bfs(g, 0))


def test_out_of_range_source_rejected(tiny_graph):
    # XLA's .at[].set clips out-of-range indices into the sentinel slot;
    # without host-side validation that silently returns "all unreachable".
    with pytest.raises(ValueError):
        bfs(tiny_graph, 99)
    with pytest.raises(ValueError):
        SuperstepRunner(tiny_graph).init(-1)
