"""Tests for bfs_tpu.analysis: the static rules (each must trip on a
fixture and stay quiet on its near-miss), the committed-baseline
mechanism, the repo self-lint (tier-1's "the tree is clean modulo
baseline" gate), the CLI exit codes, and the runtime sanitizers
(transfer guard + retrace counters) under JAX_PLATFORMS=cpu."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from bfs_tpu.analysis import (
    Baseline,
    analyze_file,
    analyze_paths,
    default_baseline_path,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(tmp_path, code: str, name: str = "snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    return analyze_file(str(p), str(tmp_path))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Transfer / trace-safety rules.
# ---------------------------------------------------------------------------

def test_trc001_item_in_hot_region(tmp_path):
    fs = lint(tmp_path, """
        # bfs_tpu: hot
        def tick(x):
            return x.item()
        """)
    assert rules_of(fs) == ["TRC001"]


def test_trc001_near_miss_outside_hot_region(tmp_path):
    fs = lint(tmp_path, """
        def report(x):
            return x.item()
        """)
    assert fs == []


def test_trc002_conversion_trips_constant_does_not(tmp_path):
    fs = lint(tmp_path, """
        # bfs_tpu: hot
        def tick(x):
            return float(x)

        # bfs_tpu: hot
        def sized(x):
            return int(1e9)

        # bfs_tpu: hot
        def mixed(x):
            return int(x, 10)
        """)
    # One literal argument must not whitelist a mixed call (``int(x, 10)``).
    assert [(f.rule, f.line) for f in fs] == [("TRC002", 4), ("TRC002", 12)]


def test_trc003_materializer(tmp_path):
    fs = lint(tmp_path, """
        import numpy as np

        # bfs_tpu: hot
        def tick(x):
            return np.asarray(x)
        """)
    assert rules_of(fs) == ["TRC003"]


def test_trc004_device_get_and_ok_pragma(tmp_path):
    fs = lint(tmp_path, """
        import jax

        # bfs_tpu: hot
        def tick(x):
            return jax.device_get(x)

        # bfs_tpu: hot
        def tock(x):
            return jax.device_get(x)  # bfs_tpu: ok TRC004 intended reply pull
        """)
    assert [f.rule for f in fs] == ["TRC004"]
    assert fs[0].line == 6  # the unsuppressed one


def test_trc005_print_in_hot_span(tmp_path):
    fs = lint(tmp_path, """
        def bench(run, roots):
            # bfs_tpu: hot-start
            for _ in range(3):
                out = run(roots)
                print(out)
            # bfs_tpu: hot-end
            print("done")  # outside the span: fine
        """)
    assert [f.rule for f in fs] == ["TRC005"]
    assert fs[0].line == 6


def test_prg001_overlapping_hot_start_flagged_and_covered(tmp_path):
    # A duplicated hot-start (or deleted hot-end) must not silently drop
    # the first span from coverage: the span still polices (TRC003 below
    # fires in BOTH halves) and PRG001 names the malformed pragma.
    fs = lint(tmp_path, """
        import numpy as np

        def bench(x):
            # bfs_tpu: hot-start
            a = np.asarray(x)
            # bfs_tpu: hot-start
            b = np.asarray(x)
            # bfs_tpu: hot-end
            return a, b
        """)
    assert rules_of(fs) == ["PRG001", "TRC003"]
    assert sum(f.rule == "TRC003" for f in fs) == 2


def test_trc006_python_branch_on_traced_value(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            m = jnp.any(x)
            if m:
                return x + 1
            return x
        """)
    assert rules_of(fs) == ["TRC006"]


def test_trc006_near_miss_container_iteration(tmp_path):
    # Iterating a pytree container param / static-config branches is the
    # bread and butter of kernel signatures — must NOT trip.
    fs = lint(tmp_path, """
        import jax

        @jax.jit
        def step(x, folds, axis_name=None):
            for fold in folds:
                x = x + fold
            if axis_name is not None:
                x = jax.lax.pmin(x, axis_name)
            return x
        """)
    assert fs == []


def test_hot_traced_pragma_enables_trc006(tmp_path):
    fs = lint(tmp_path, """
        import jax.numpy as jnp

        # bfs_tpu: hot traced
        def kernel(x):
            m = jnp.any(x)
            while m:
                x = x - 1
            return x
        """)
    assert "TRC006" in rules_of(fs)


# ---------------------------------------------------------------------------
# Recompile-drift rules.
# ---------------------------------------------------------------------------

def test_rcd001_jit_lambda_in_function(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def serve_tick(x):
            f = jax.jit(lambda a: a + 1)
            return f(x)
        """)
    assert rules_of(fs) == ["RCD001"]


def test_rcd001_sees_through_inline_decorator_wrap(tmp_path):
    # jit(traced("x")(lambda ...)) is exactly as fresh an identity per
    # call as the bare lambda — the wrapper must not hide it.
    fs = lint(tmp_path, """
        import jax

        def serve_tick(x):
            f = jax.jit(traced("tick")(lambda a: a + 1))
            return f(x)
        """)
    assert rules_of(fs) == ["RCD001"]


def test_rcd001_near_miss_module_level(tmp_path):
    fs = lint(tmp_path, """
        import jax

        f = jax.jit(lambda a: a + 1)

        def serve_tick(x):
            return f(x)
        """)
    assert fs == []


def test_rcd002_computed_static_argnames(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def build(fn, names):
            return jax.jit(fn, static_argnames=tuple(names))

        def build_ok(fn):
            return jax.jit(fn, static_argnames=("num_vertices",))
        """)
    assert [f.rule for f in fs] == ["RCD002"]
    assert fs[0].line == 5


def test_rcd003_jit_in_loop(tmp_path):
    fs = lint(tmp_path, """
        import jax

        def sweep(fns, x):
            outs = []
            for fn in fns:
                outs.append(jax.jit(fn)(x))
            return outs
        """)
    assert "RCD003" in rules_of(fs)


def test_rcd004_computed_key_element(tmp_path):
    fs = lint(tmp_path, """
        def tick(exe_cache, build, n, graph):
            padded = bucket_for(n)
            runner, hit = exe_cache.get((graph, padded), build)
            return runner
        """)
    assert rules_of(fs) == ["RCD004"]
    assert fs[0].severity == "warning"


def test_rcd005_underkeyed_build_closure(tmp_path):
    # ``engine`` is derived per call but missing from the key — two calls
    # differing only in engine would share one executable.
    fs = lint(tmp_path, """
        def tick(exe_cache, registry, graph, n, engine_cfg):
            padded = n
            engine = pick_engine(engine_cfg)
            runner, hit = exe_cache.get(
                (graph, padded),
                lambda: build_batch_runner(registry, graph, engine, padded),
            )
            return runner
        """)
    assert "RCD005" in rules_of(fs)
    assert any("engine" in f.message for f in fs if f.rule == "RCD005")


def test_rcd005_near_miss_fully_keyed(tmp_path):
    # Same closure with engine in the key — and the ambient ``registry``
    # handle (a bare parameter) never counts as a key obligation.
    fs = lint(tmp_path, """
        def tick(exe_cache, registry, graph, n, engine_cfg):
            padded = n
            engine = pick_engine(engine_cfg)
            runner, hit = exe_cache.get(
                (graph, engine, padded),
                lambda: build_batch_runner(registry, graph, engine, padded),
            )
            return runner
        """)
    assert "RCD005" not in rules_of(fs)


# ---------------------------------------------------------------------------
# Lock-discipline rules.
# ---------------------------------------------------------------------------

_LOCK_CLASS = """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._entries = {{}}  # guarded-by: _lock

        def get(self, k):
            {get_body}

        def put(self, k, v):
            with self._lock:
                self._entries[k] = v
"""


def test_lck001_unguarded_access(tmp_path):
    fs = lint(tmp_path, _LOCK_CLASS.format(get_body="return self._entries.get(k)"))
    assert rules_of(fs) == ["LCK001"]
    assert "Cache.get()" in fs[0].message


def test_lck001_near_miss_guarded(tmp_path):
    fs = lint(
        tmp_path,
        _LOCK_CLASS.format(
            get_body="with self._lock:\n                return self._entries.get(k)"
        ),
    )
    assert fs == []


def test_lck001_condition_alias_counts_as_lock(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []  # guarded-by: _lock

            def pop(self):
                with self._cond:
                    return self._items.pop()
        """)
    assert fs == []


def test_lck001_holds_pragma(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._resident = {}  # guarded-by: _lock

            # bfs_tpu: holds _lock
            def _evict(self, k):
                self._resident.pop(k)

            def release(self, k):
                with self._lock:
                    self._evict(k)
        """)
    assert fs == []


def test_lck001_module_level_global(tmp_path):
    fs = lint(tmp_path, """
        import threading

        _lock = threading.Lock()
        _counters = {}  # guarded-by: _lock

        def bump(name):
            _counters[name] = _counters.get(name, 0) + 1

        def bump_ok(name):
            with _lock:
                _counters[name] = _counters.get(name, 0) + 1
        """)
    # One finding per (line, field): bump()'s read+write share a line.
    assert [f.rule for f in fs] == ["LCK001"]
    assert "bump()" in fs[0].message


def test_lck002_unannotated_mutable_field(tmp_path):
    fs = lint(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = []
        """)
    assert rules_of(fs) == ["LCK002"]
    assert fs[0].severity == "warning"


def test_lck002_near_miss_no_lock_owned(tmp_path):
    fs = lint(tmp_path, """
        class Plain:
            def __init__(self):
                self.items = []
        """)
    assert fs == []


# ---------------------------------------------------------------------------
# Observability discipline (OBS001).
# ---------------------------------------------------------------------------

def test_obs001_telemetry_read_in_hot_region(tmp_path):
    fs = lint(tmp_path, """
        from bfs_tpu.obs.telemetry import read_telemetry

        # bfs_tpu: hot
        def tick(state, acc):
            fv = read_telemetry(acc)
            return state, fv
        """)
    assert rules_of(fs) == ["OBS001"]


def test_obs001_metrics_reads_in_hot_span(tmp_path):
    fs = lint(tmp_path, """
        def bench(run, roots, registry):
            # bfs_tpu: hot-start
            for _ in range(3):
                out = run(roots)
                snap = registry.snapshot()
            # bfs_tpu: hot-end
            return snap
        """)
    assert [f.rule for f in fs] == ["OBS001"]
    assert fs[0].line == 6


def test_obs001_near_miss_read_at_loop_exit(tmp_path):
    # The CONTRACT: the same read immediately AFTER the hot region (loop
    # exit) is the intended one pull — never flagged.
    fs = lint(tmp_path, """
        from bfs_tpu.obs.telemetry import read_telemetry

        def run(fused, src):
            # bfs_tpu: hot-start
            state, acc = fused(src)
            # bfs_tpu: hot-end
            return read_telemetry((acc, state.level))
        """)
    assert fs == []


def test_obs001_span_writes_allowed_in_hot_region(tmp_path):
    # Span/counter WRITES are host-side appends — wrapping the timed
    # region in a span is the intended usage and must stay clean.
    fs = lint(tmp_path, """
        from bfs_tpu.obs.spans import span, instant

        def bench(run, roots):
            # bfs_tpu: hot-start
            with span("bench.repeat"):
                out = run(roots)
            instant("marker")
            # bfs_tpu: hot-end
            return out
        """)
    assert fs == []


# ---------------------------------------------------------------------------
# Baseline mechanism.
# ---------------------------------------------------------------------------

def test_baseline_accepts_and_reports_stale(tmp_path):
    fs = lint(tmp_path, """
        # bfs_tpu: hot
        def tick(x):
            return x.item()
        """)
    [f] = fs
    bl_path = tmp_path / "baseline.txt"
    bl_path.write_text(
        f"{f.rule}  {f.fingerprint()}  accepted for the test\n"
        "TRC001  deadbeef0000  a stale entry\n"
    )
    bl = Baseline.load(str(bl_path))
    assert bl.accepts(f)
    assert bl.stale() == ["deadbeef0000"]


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    [f1] = lint(tmp_path, """
        # bfs_tpu: hot
        def tick(x):
            return x.item()
        """, name="a.py")
    [f2] = lint(tmp_path, """
        # a new comment block
        # pushing everything down

        # bfs_tpu: hot
        def tick(x):
            return x.item()
        """, name="a.py")
    assert f1.line != f2.line
    assert f1.fingerprint() == f2.fingerprint()


# ---------------------------------------------------------------------------
# Self-lint: the shipped tree is clean modulo the committed baseline.
# ---------------------------------------------------------------------------

def test_repo_self_lint_clean_modulo_baseline():
    paths = [
        os.path.join(REPO, "bfs_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "bench.py"),
    ]
    findings = analyze_paths([p for p in paths if os.path.exists(p)], REPO)
    baseline = Baseline.load(default_baseline_path())
    # accepts() over EVERY finding (warnings too — a baselined RCD004 is
    # a warning) so stale() below reflects what the CLI would see.
    fresh = [f for f in findings if not baseline.accepts(f)]
    fresh_errors = [f for f in fresh if f.severity == "error"]
    assert fresh_errors == [], "\n".join(f.render() for f in fresh_errors)
    # Stale AST entries fail the self-lint too (ISSUE 8 satellite): an
    # accepted finding that no longer exists must be pruned, or the
    # baseline rots into a list of things nobody can re-triage.  IR/HLO
    # entries are not exercised by this pass and don't count here (their
    # own self-lint tests enforce staleness for their families).
    stale_ast = [
        fp for fp in baseline.stale()
        if not (baseline.entries[fp][0].startswith("IR")
                or baseline.entries[fp][0].startswith("HLO")
                or baseline.entries[fp][0].startswith("PAL"))
    ]
    assert stale_ast == [], (
        "stale baseline entries (fixed or edited — prune them): "
        + ", ".join(stale_ast)
    )


def test_repo_has_expected_hot_coverage():
    """The regions the ISSUE names must actually be declared hot —
    a deleted pragma should fail loudly here, not silently shrink
    coverage."""
    from bfs_tpu.analysis.core import SourceFile, hot_regions

    expectations = {
        "bfs_tpu/ops/relax.py": (
            "relax_superstep",
            # the packed fused-word kernels (ISSUE 5) must keep
            # transfer-guard coverage — deleting a pragma fails here
            "relax_superstep_packed",
            "apply_candidates_packed",
        ),
        "bfs_tpu/ops/pull.py": (
            "relax_pull_superstep",
            "relax_pull_superstep_packed",
        ),
        "bfs_tpu/ops/relay.py": (
            "rowmin_ranks",
            "apply_relay_candidates_packed",
            "relay_superstep_words_packed",
            # the bounded-segment reference runners (ISSUE 14) iterate
            # the same hot bodies — they must stay transfer-policed
            "relay_segment_words",
            "relay_segment_words_packed",
        ),
        # the per-phase Pallas kernels (ISSUE 7) run inside the fused
        # hot loop when selected — they must keep static hot coverage,
        # INCLUDING the inner pallas kernel bodies PR 7 added (the
        # tournament and packed-update kernels are both named `kernel`;
        # the pin lagged them — ISSUE 8 satellite)
        "bfs_tpu/ops/relay_pallas.py": (
            "rowmin_ranks_pallas",
            "apply_relay_candidates_packed_pallas",
            "kernel",
        ),
        # the direction predicate and its mass inputs compile into every
        # auto-mode while_loop body (ISSUE 7 tentpole a), and the
        # combined-layout fused program itself is jit-hot (ISSUE 8
        # satellite: the pin lagged PR 7's program)
        "bfs_tpu/models/direction.py": (
            "take_pull",
            "frontier_masses",
            "_bfs_direction_fused",
        ),
        "bfs_tpu/models/bfs.py": ("_frontier_masses_words",),
        # the MXU expansion arm (ISSUE 15): the kernel, its XLA twin and
        # the superstep wrappers all run inside the fused hot loop when
        # the arm is selected — they must keep static hot coverage
        "bfs_tpu/ops/relay_mxu.py": (
            "expand_frontier_mxu",
            "expand_frontier_mxu_xla",
            "mxu_superstep_packed",
            "mxu_superstep",
            "kernel",
        ),
        "bfs_tpu/obs/telemetry.py": ("record_direction",),
        "bfs_tpu/serve/executor.py": ("_state_to_result",),
        # the device layout-builder programs (ISSUE 10 tentpole) are the
        # first-touch build path — they must stay transfer-policed and
        # IR-registered; deleting a pragma fails here
        "bfs_tpu/graph/relay_device.py": (
            "_degree_hist_program",
            "_relabel_program",
            "_slots_program",
            "_net_assembly_program",
            "_vperm_assembly_program",
            "_csr_program",
            "_route_level_program",
            "_route_mid_program",
            "_compact_program",
        ),
    }
    for rel, fn_names in expectations.items():
        src = SourceFile(os.path.join(REPO, rel), REPO)
        names = {r.name for r in hot_regions(src)}
        for fn_name in fn_names:
            assert fn_name in names, (rel, fn_name, sorted(names))
    bench = SourceFile(os.path.join(REPO, "bfs_tpu/bench.py"), REPO)
    spans = [r for r in hot_regions(bench) if r.name.startswith("span@")]
    assert len(spans) >= 2, "bench timed-repeat hot spans went missing"
    # EVERY Pallas kernel body is hot (ISSUE 13 satellite: the Beneš
    # route kernels — tile-major local, per-stage local/outer, elem —
    # lagged the tournament/packed-update pair; all five inner bodies
    # are named `kernel`, so the pin is a count, not a name).
    rp = SourceFile(
        os.path.join(REPO, "bfs_tpu/ops/relay_pallas.py"), REPO
    )
    kernel_bodies = [r for r in hot_regions(rp) if r.name == "kernel"]
    assert len(kernel_bodies) >= 5, (
        "a Pallas kernel body lost its hot pragma",
        sorted(r.start for r in kernel_bodies),
    )


# ---------------------------------------------------------------------------
# CLI.
# ---------------------------------------------------------------------------

def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), *args],
        capture_output=True, text=True, cwd=cwd, timeout=120,
    )


def test_cli_exit_zero_on_repo():
    proc = _run_cli([])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exit_nonzero_on_each_rule_fixture(tmp_path):
    fixtures = {
        "trc001.py": "# bfs_tpu: hot\ndef f(x):\n    return x.item()\n",
        "trc002.py": "# bfs_tpu: hot\ndef f(x):\n    return float(x)\n",
        "trc003.py": "import numpy as np\n# bfs_tpu: hot\ndef f(x):\n    return np.asarray(x)\n",
        "trc004.py": "import jax\n# bfs_tpu: hot\ndef f(x):\n    return jax.device_get(x)\n",
        "trc005.py": "def f(x):\n    # bfs_tpu: hot-start\n    print(x)\n    # bfs_tpu: hot-end\n",
        "trc006.py": (
            "import jax\nimport jax.numpy as jnp\n@jax.jit\ndef f(x):\n"
            "    m = jnp.any(x)\n    if m:\n        return x\n    return x + 1\n"
        ),
        "rcd001.py": "import jax\ndef f(x):\n    return jax.jit(lambda a: a)(x)\n",
        "rcd002.py": (
            "import jax\ndef f(fn, names):\n"
            "    return jax.jit(fn, static_argnames=tuple(names))\n"
        ),
        "rcd003.py": (
            "import jax\ndef f(fns, x):\n    return [jax.jit(g)(x) for g in fns]\n"
        ),
        "rcd005.py": (
            "def f(exe_cache, g, cfg, n):\n    padded = n\n    eng = pick(cfg)\n"
            "    return exe_cache.get((g, padded), lambda: build(g, eng, padded))\n"
        ),
        "lck001.py": (
            "import threading\nclass C:\n    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.d = {}  # guarded-by: _lock\n"
            "    def g(self):\n        return self.d\n"
        ),
        "obs001.py": (
            "from bfs_tpu.obs.telemetry import read_telemetry\n"
            "# bfs_tpu: hot\ndef f(state, acc):\n"
            "    return read_telemetry(acc)\n"
        ),
    }
    assert len(fixtures) >= 8
    for name, code in fixtures.items():
        p = tmp_path / name
        p.write_text(code)
        proc = _run_cli([str(p), "--root", str(tmp_path), "--no-baseline"])
        assert proc.returncode == 1, (name, proc.stdout, proc.stderr)
        # RCD003's list-comp fixture legitimately also reports RCD001.
        expected = name.split(".")[0].upper()
        assert expected in proc.stdout, (name, proc.stdout)


def test_cli_rules_catalog():
    proc = _run_cli(["--rules"])
    assert proc.returncode == 0
    for rule in ("TRC001", "TRC006", "RCD001", "RCD005", "LCK001", "LCK002",
                 "OBS001", "IR001", "IR004", "IR006", "HLO001", "HLO003",
                 "HLO005", "PAL001", "PAL003", "PAL005"):
        assert rule in proc.stdout


def test_cli_stale_baseline_fails_default_run(tmp_path):
    """A baseline entry whose fingerprint matches nothing is an ERROR on
    a default-surface run (ISSUE 8 satellite: stale entries used to be
    only reported) — but an explicit-path run proves nothing about the
    rest of the tree and must not trip on it."""
    bl = tmp_path / "baseline.txt"
    shipped = open(
        os.path.join(REPO, "bfs_tpu", "analysis", "baseline.txt"),
        encoding="utf-8",
    ).read()
    bl.write_text(shipped + "TRC001  deadbeef0000  a dead entry\n")
    proc = _run_cli(["--baseline", str(bl)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "STALE" in proc.stderr
    # Same baseline, single-file target: stale not enforced.
    proc = _run_cli([
        os.path.join(REPO, "tools", "ledger_compare.py"),
        "--baseline", str(bl),
    ])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_write_baseline_carries_ir_and_hlo_entries_over(tmp_path):
    """The AST --write-baseline regenerates its own section but must not
    drop the hand-curated IR, HLO *or* Pallas entries sharing the file
    (ISSUE 12/13 satellites: PR 8 special-cased IR only)."""
    bl = tmp_path / "baseline.txt"
    shipped = open(
        os.path.join(REPO, "bfs_tpu", "analysis", "baseline.txt"),
        encoding="utf-8",
    ).read()
    bl.write_text(shipped
                  + "IR001  cafecafe0000  fixture: justified\n"
                  + "HLO003  beefbeef0000  fixture: also justified\n"
                  + "PAL002  feedfeed0000  fixture: pal justified\n")
    proc = _run_cli(["--write-baseline", "--baseline", str(bl)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rewritten = bl.read_text()
    assert "IR001  cafecafe0000  fixture: justified" in rewritten
    assert "HLO003  beefbeef0000  fixture: also justified" in rewritten
    assert "PAL002  feedfeed0000  fixture: pal justified" in rewritten
    # The shipped HLO and Pallas sections' real entries survive too.
    assert "HLO003  15602bda2246" in rewritten
    assert "PAL002  32cd6b364883" in rewritten
    assert "carried over" in proc.stdout


def test_cli_changed_lints_only_diffed_files(tmp_path):
    """--changed on a clean tree (or outside git) lints nothing and
    exits 0 — the pre-commit fast path."""
    import tempfile

    with tempfile.TemporaryDirectory() as empty:
        os.makedirs(os.path.join(empty, "bfs_tpu"), exist_ok=True)
        proc = _run_cli(["--changed", "--root", empty])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no changed python files" in proc.stderr


def test_changed_files_scope_excludes_tests():
    """_changed_files keeps only the default lint surface: a changed
    tests/ file (whose fixtures deliberately trip rules) must never fail
    the --changed fast path."""
    from unittest import mock

    from bfs_tpu.analysis.__main__ import _changed_files

    diff = "tests/test_analysis_ir.py\nbfs_tpu/models/bfs.py\n" \
           "tools/lint.py\nbench.py\nREADME.md\n"
    done = mock.Mock(returncode=0, stdout=diff)
    with mock.patch("subprocess.run", return_value=done), \
         mock.patch("os.path.exists", return_value=True):
        rels = [os.path.relpath(p, REPO) for p in _changed_files(REPO)]
    assert rels == ["bfs_tpu/models/bfs.py", "tools/lint.py", "bench.py"]


# ---------------------------------------------------------------------------
# Runtime sanitizers (CPU jax).
# ---------------------------------------------------------------------------

def test_transfer_guard_off_by_default(monkeypatch):
    monkeypatch.delenv("BFS_TPU_TRANSFER_GUARD", raising=False)
    import jax.numpy as jnp

    from bfs_tpu.analysis.runtime import guarded_region, transfer_guard_level

    assert transfer_guard_level() is None
    with guarded_region("test"):
        assert jnp.arange(4)[0].item() == 0  # no guard: sync is allowed


def test_transfer_guard_catches_item(monkeypatch):
    monkeypatch.setenv("BFS_TPU_TRANSFER_GUARD", "1")
    import jax.numpy as jnp

    from bfs_tpu.analysis.runtime import guarded_region

    a = jnp.arange(8)
    with pytest.raises(Exception, match="transfer-guard:deliberate"):
        with guarded_region("deliberate"):
            a[0].item()
    # The guard is scoped: the same conversion outside raises nothing.
    assert a[0].item() == 0


def test_transfer_guard_leaves_unrelated_errors_alone(monkeypatch):
    """Only genuine guard violations get the region-name relabel; a
    workload error raised inside the region must pass through untouched
    (downstream error classifiers match on message text)."""
    monkeypatch.setenv("BFS_TPU_TRANSFER_GUARD", "1")
    from bfs_tpu.analysis.runtime import guarded_region

    with pytest.raises(ValueError) as exc_info:
        with guarded_region("some-region"):
            raise ValueError("workload exploded")
    assert str(exc_info.value) == "workload exploded"


def test_transfer_guard_allows_explicit_transfers(monkeypatch):
    monkeypatch.setenv("BFS_TPU_TRANSFER_GUARD", "1")
    import jax
    import numpy as np

    from bfs_tpu.analysis.runtime import guarded_region

    with guarded_region("explicit-ok"):
        dev = jax.device_put(np.arange(4))
        # NB ``dev * 2`` would implicitly upload the host scalar 2 and
        # trip the guard — the eager op must stay device-only.
        host = jax.device_get(dev + dev)
    assert list(host) == [0, 2, 4, 6]


def test_serve_batch_path_guard_clean(monkeypatch):
    """The serve device batch path must run transfer-clean under the
    guard: one explicit upload, one explicit device-sliced pull."""
    monkeypatch.setenv("BFS_TPU_TRANSFER_GUARD", "1")
    import numpy as np

    from bfs_tpu.graph.generators import rmat_graph
    from bfs_tpu.oracle.bfs import queue_bfs
    from bfs_tpu.serve import BfsServer

    graph = rmat_graph(6, 4, seed=3)
    with BfsServer(engine="pull", max_batch=4) as server:
        server.register("g", graph)
        reply = server.query("g", 0).result(timeout=120)
    expect = queue_bfs(graph, 0)[0]
    assert np.array_equal(reply.dist, expect)


def test_retrace_counter_names_function():
    import jax
    import jax.numpy as jnp

    from bfs_tpu.analysis.runtime import (
        format_retrace_report,
        retrace_report,
        traced,
    )

    @jax.jit
    @traced("test.retrace_probe")
    def f(x):
        return x * 2

    before = retrace_report().get("test.retrace_probe", 0)
    f(jnp.arange(4))
    f(jnp.arange(4))  # same shape: cached, no retrace
    mid = retrace_report()["test.retrace_probe"]
    assert mid == before + 1
    f(jnp.arange(8))  # new shape: one more trace
    after = retrace_report()["test.retrace_probe"]
    assert after == mid + 1
    report = format_retrace_report(baseline={"test.retrace_probe": before})
    assert "test.retrace_probe" in report
    assert f"+{after - before}" in report


def test_hot_region_decorator_registers_and_statically_hot(tmp_path):
    from bfs_tpu.analysis.runtime import hot_region, hot_registry

    @hot_region(name="test.region")
    def fn(x):
        return x

    assert fn(3) == 3
    assert "test.region" in hot_registry()
    fs = lint(tmp_path, """
        from bfs_tpu.analysis.runtime import hot_region

        @hot_region
        def tick(x):
            return x.item()
        """)
    assert rules_of(fs) == ["TRC001"]


# ---------------------------------------------------------------------------
# Lock-order recorder (ISSUE 12 satellite): the dynamic complement to
# LCK001/002 — order, not coverage.
# ---------------------------------------------------------------------------

def test_make_lock_plain_when_disabled(monkeypatch):
    import threading

    from bfs_tpu.analysis.runtime import make_lock

    monkeypatch.delenv("BFS_TPU_LOCK_ORDER", raising=False)
    assert isinstance(make_lock("x"), type(threading.Lock()))
    assert isinstance(make_lock("x", "rlock"), type(threading.RLock()))


def test_lock_order_cycle_detected_across_threads(monkeypatch):
    import threading

    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    A, B = art.make_lock("fx.A"), art.make_lock("fx.B")

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass

    for target in (ab, ba):
        t = threading.Thread(target=target)
        t.start()
        t.join()
    report = art.lock_order_report()
    assert report["edges"] == {"fx.A->fx.B": 1, "fx.B->fx.A": 1}
    assert report["cycles"] == [["fx.A", "fx.B", "fx.A"]]
    with pytest.raises(art.LockOrderError, match="fx.A -> fx.B -> fx.A"):
        art.assert_lock_order_clean()
    art.reset_lock_order()


def test_lock_order_consistent_nesting_is_clean(monkeypatch):
    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    A, B, C = (art.make_lock(n) for n in ("fx.A", "fx.B", "fx.C"))
    for _ in range(3):  # same A -> B -> C order every time: no cycle
        with A:
            with B:
                with C:
                    pass
    report = art.lock_order_report()
    assert report["cycles"] == []
    assert set(report["edges"]) == {"fx.A->fx.B", "fx.A->fx.C",
                                    "fx.B->fx.C"}
    art.assert_lock_order_clean()
    art.reset_lock_order()


def test_lock_order_reentrant_rlock_records_nothing(monkeypatch):
    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    R = art.make_lock("fx.R", "rlock")
    with R:
        with R:  # reentrant re-acquire orders nothing
            pass
    assert art.lock_order_report() == {"edges": {}, "cycles": []}
    art.reset_lock_order()


def test_lock_order_raise_mode_raises_at_the_acquire(monkeypatch):
    import threading

    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "raise")
    art.reset_lock_order()
    A, B = art.make_lock("fx.A"), art.make_lock("fx.B")

    def ab():
        with A:
            with B:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join()
    with B:
        with pytest.raises(art.LockOrderError, match="cycle"):
            A.acquire()
    art.reset_lock_order()


def test_lock_order_condition_over_recorded_lock(monkeypatch):
    """server.py builds threading.Condition(self._lock) — the proxy must
    keep that working (wait/notify round-trip through a recorded lock)."""
    import threading

    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    L = art.make_lock("fx.cond_lock")
    cond = threading.Condition(L)
    hits = []

    def waiter():
        with cond:
            while not hits:
                if not cond.wait(timeout=5.0):
                    return
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    with cond:
        hits.append("set")
        cond.notify()
    t.join(timeout=5.0)
    assert hits == ["set", "woke"]
    assert art.lock_order_report()["cycles"] == []
    art.reset_lock_order()


def test_hlo_fingerprints_pin_program_specs_coverage():
    """Deleting a PROGRAM_SPECS entry or its committed HLO fingerprint
    row fails tier-1 (ISSUE 12 satellite) — the two sets must stay equal
    and at least as large as the ISSUE 11 pin.  Importing the registry
    NAMES needs no jax (the builders are lazy)."""
    from bfs_tpu.analysis.ir import PROGRAM_SPECS

    path = os.path.join(REPO, "bfs_tpu", "analysis",
                        "hlo_fingerprints.json")
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    committed = set(doc["programs"])
    registry = set(PROGRAM_SPECS)
    # ISSUE 11 pinned 25; ISSUE 14 adds the four segment programs.
    assert len(registry) >= 32
    assert registry - committed == set(), (
        "programs missing HLO fingerprint coverage — run "
        "`bfs-tpu-lint --hlo --update-fingerprints`"
    )
    assert committed - registry == set(), (
        "committed fingerprints for programs the registry no longer "
        "declares — a hot program silently left PROGRAM_SPECS"
    )
    for name, row in doc["programs"].items():
        assert {"temp_bytes", "fusions", "loop_collectives",
                "loop_materializations"} <= set(row), name


def test_lock_order_nonblocking_probe_records_no_edge(monkeypatch):
    """Condition._is_owned probes with acquire(0) while holding arbitrary
    other locks — a try-acquire can never be the blocked arm of a
    deadlock, so it must not fabricate (reversed) ordering edges."""
    from bfs_tpu.analysis import runtime as art

    monkeypatch.setenv("BFS_TPU_LOCK_ORDER", "1")
    art.reset_lock_order()
    A, B = art.make_lock("fx.A"), art.make_lock("fx.B")
    with A:
        with B:
            pass  # genuine blocking edge A -> B
    with B:
        assert A.acquire(False)  # probe: succeeds, but orders nothing
        A.release()
    report = art.lock_order_report()
    assert report["edges"] == {"fx.A->fx.B": 1}  # no fx.B->fx.A
    assert report["cycles"] == []
    art.reset_lock_order()


def test_lck002_sees_make_lock_as_lock_owner(tmp_path):
    """Classes that build their lock through analysis.runtime.make_lock
    (the lock-order recorder factory) still OWN a lock — an unannotated
    mutable field must keep its LCK002 warning."""
    fs = lint(tmp_path, """
        from bfs_tpu.analysis.runtime import make_lock

        class C:
            def __init__(self):
                self._lock = make_lock("c._lock")
                self.pending = {}

            def g(self):
                return self.pending
        """)
    assert "LCK002" in rules_of(fs)
