"""Weighted SSSP on the semiring substrate (ISSUE 16).

Covers: min-plus supersteps vs the host Dijkstra oracle (dist AND
canonical parents, bit-for-bit) on star/path/gnm/rmat; delta-stepping
bucket invariance (delta in {1, 17, inf, default} -> one fixpoint); the
packed16 (dist:16|parent:16) arm's schedule identity with the unpacked
carry; the truncation canary -> unpacked fallback; fused-vs-segmented
bit-identity incl. the in-process kill/resume chaos smoke; x2/x8
edge-sharded parity; the on-device invariant counters; and the semiring
registry / hash-weight / delta-knob contracts.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from bfs_tpu.algo import (
    DEFAULT_MAX_WEIGHT,
    SEMIRINGS,
    edge_weights_np,
    resolve_delta,
    sssp,
    sssp_segmented,
    sssp_sharded,
)
from bfs_tpu.algo.sssp import PACKED16_MAX_V, packed16_fits
from bfs_tpu.algo.substrate import edge_weights
from bfs_tpu.graph.csr import INF_DIST
from bfs_tpu.graph.generators import (
    gnm_graph,
    path_graph,
    rmat_graph,
    star_graph,
)
from bfs_tpu.oracle import check_sssp, dijkstra, sssp_device_check
from bfs_tpu.resilience import faults
from bfs_tpu.resilience.faults import FaultInjected
from bfs_tpu.resilience.superstep_ckpt import CkptConfig, SuperstepCheckpointer

MAXW = 31
SOURCE = 3

GRAPHS = {
    "star": lambda: star_graph(64),
    "path": lambda: path_graph(200),
    "gnm": lambda: gnm_graph(300, 2100, seed=5),
    "rmat": lambda: rmat_graph(7, 8, seed=2),
}

_cache: dict[str, object] = {}


@pytest.fixture(params=sorted(GRAPHS))
def graph(request):
    if request.param not in _cache:
        _cache[request.param] = GRAPHS[request.param]()
    return _cache[request.param]


def _oracle(graph, source=SOURCE, max_weight=MAXW):
    w = edge_weights_np(graph.src, graph.dst, max_weight)
    return dijkstra(graph, w, source)


def _mgr(tmp_path, k=1, config=None):
    return SuperstepCheckpointer(
        tmp_path, config if config is not None else {"algo": "sssp"},
        cfg=CkptConfig("every", k),
    )


# ------------------------------------------------------------- substrate --
def test_semiring_registry():
    assert set(SEMIRINGS) == {"bfs", "sssp", "cc"}
    # Only valueless contributions ride the AND/popcount MXU tiles.
    assert SEMIRINGS["bfs"].mxu_eligible
    assert not SEMIRINGS["sssp"].mxu_eligible
    assert not SEMIRINGS["cc"].mxu_eligible
    assert SEMIRINGS["bfs"].packable and SEMIRINGS["sssp"].packable
    assert not SEMIRINGS["cc"].packable


def test_edge_weights_host_device_parity(graph):
    w_np = edge_weights_np(graph.src, graph.dst, MAXW)
    w_dev = np.asarray(
        edge_weights(jnp.asarray(graph.src), jnp.asarray(graph.dst), MAXW)
    )
    np.testing.assert_array_equal(w_np, w_dev.astype(w_np.dtype))
    assert int(w_np.min()) >= 1 and int(w_np.max()) <= MAXW


def test_resolve_delta_knob(monkeypatch):
    monkeypatch.delenv("BFS_TPU_SSSP_DELTA", raising=False)
    assert resolve_delta() == 64
    assert resolve_delta(17) == 17
    assert resolve_delta("inf") == 2**31 - 1
    assert resolve_delta(0) == 2**31 - 1
    monkeypatch.setenv("BFS_TPU_SSSP_DELTA", "9")
    assert resolve_delta() == 9
    monkeypatch.setenv("BFS_TPU_SSSP_DELTA", "inf")
    assert resolve_delta() == 2**31 - 1


def test_packed16_gate():
    assert packed16_fits(PACKED16_MAX_V - 1)
    assert not packed16_fits(PACKED16_MAX_V)


# -------------------------------------------------------- oracle parity --
@pytest.mark.algo_smoke
@pytest.mark.parametrize("packed", [False, True])
def test_sssp_matches_dijkstra(graph, packed):
    odist, opar = _oracle(graph)
    res = sssp(graph, SOURCE, max_weight=MAXW, packed=packed)
    np.testing.assert_array_equal(res.dist, odist)
    np.testing.assert_array_equal(res.parent, opar)
    assert res.packed is packed
    assert res.truncated_fallbacks == 0
    w = edge_weights_np(graph.src, graph.dst, MAXW)
    assert check_sssp(graph, w, res.dist, res.parent, SOURCE) == []


@pytest.mark.parametrize("delta", [1, 17, "inf"])
def test_delta_bucket_invariance(graph, delta):
    # Any bucket width reaches the same min-plus fixpoint; parents come
    # from the exit-time canonicalization, so they match too.
    odist, opar = _oracle(graph)
    res = sssp(graph, SOURCE, max_weight=MAXW, delta=delta, packed=False)
    np.testing.assert_array_equal(res.dist, odist)
    np.testing.assert_array_equal(res.parent, opar)


def test_packed_schedule_identity(graph):
    # The packed merge is strict on the dist field, so the frontier
    # schedule — hence the round count — is identical to unpacked.
    r_p = sssp(graph, SOURCE, max_weight=MAXW, packed=True)
    r_u = sssp(graph, SOURCE, max_weight=MAXW, packed=False)
    assert r_p.rounds == r_u.rounds
    np.testing.assert_array_equal(r_p.dist, r_u.dist)
    np.testing.assert_array_equal(r_p.parent, r_u.parent)


@pytest.mark.algo_smoke
def test_packed_truncation_falls_back_unpacked():
    # path(600) x max_weight 255: the true eccentricity overflows 16 bits
    # (the oracle proves the scenario is real), the clamp canary fires,
    # and the driver re-runs unpacked — exact, with the fallback counted.
    g = path_graph(600)
    w = edge_weights_np(g.src, g.dst, DEFAULT_MAX_WEIGHT)
    odist, opar = dijkstra(g, w, 0)
    assert int(odist[odist != INF_DIST].max()) > 0xFFFE
    res = sssp(g, 0, packed=True)
    assert res.packed is False
    assert res.truncated_fallbacks == 1
    np.testing.assert_array_equal(res.dist, odist)
    np.testing.assert_array_equal(res.parent, opar)


# ---------------------------------------------------------- device check --
def test_sssp_device_check(graph):
    res = sssp(graph, SOURCE, max_weight=MAXW, packed=False)
    assert sssp_device_check(
        graph.src, graph.dst, res.dist, res.parent, SOURCE,
        graph.num_vertices, MAXW,
    ) == {}
    bad = res.dist.copy()
    bad[SOURCE] = 1
    viol = sssp_device_check(
        graph.src, graph.dst, bad, res.parent, SOURCE,
        graph.num_vertices, MAXW,
    )
    assert viol.get("source_dist_nonzero") == 1


# ------------------------------------------------- segmented / kill-resume --
@pytest.mark.algo_smoke
@pytest.mark.parametrize("packed", [False, True])
def test_segmented_bit_identical(graph, tmp_path, packed):
    fused = sssp(graph, SOURCE, max_weight=MAXW, packed=packed)
    for k in (2, 3):
        res = sssp_segmented(
            graph, SOURCE, ckpt=_mgr(tmp_path / f"k{k}", k=k),
            max_weight=MAXW, packed=packed,
        )
        np.testing.assert_array_equal(res.dist, fused.dist)
        np.testing.assert_array_equal(res.parent, fused.parent)
        assert res.rounds == fused.rounds
        assert res.packed is fused.packed


def test_segmented_disabled_store_touches_nothing(graph, tmp_path):
    off = SuperstepCheckpointer(
        tmp_path, {"algo": "sssp"}, cfg=CkptConfig("off")
    )
    fused = sssp(graph, SOURCE, max_weight=MAXW, packed=False)
    res = sssp_segmented(
        graph, SOURCE, ckpt=off, max_weight=MAXW, packed=False
    )
    np.testing.assert_array_equal(res.dist, fused.dist)
    assert list(tmp_path.iterdir()) == []


@pytest.mark.chaos
@pytest.mark.parametrize("packed", [False, True])
def test_sssp_kill_resume_bit_identical(tmp_path, packed):
    g = GRAPHS["gnm"]()
    fused = sssp(g, SOURCE, max_weight=MAXW, packed=packed)
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            sssp_segmented(
                g, SOURCE, ckpt=_mgr(tmp_path), max_weight=MAXW,
                packed=packed,
            )
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = _mgr(tmp_path)
    res = sssp_segmented(
        g, SOURCE, ckpt=mgr, max_weight=MAXW, packed=packed
    )
    assert mgr.report()["resumed_from_epoch"] == 2
    np.testing.assert_array_equal(res.dist, fused.dist)
    np.testing.assert_array_equal(res.parent, fused.parent)
    assert res.rounds == fused.rounds


# ----------------------------------------------------------------- sharded --
@pytest.mark.algo_smoke
@pytest.mark.parametrize("shards", [2, 8])
def test_sssp_sharded_parity(graph, shards):
    base = sssp(graph, SOURCE, max_weight=MAXW, packed=False)
    res = sssp_sharded(graph, SOURCE, num_shards=shards, max_weight=MAXW)
    np.testing.assert_array_equal(res.dist, base.dist)
    np.testing.assert_array_equal(res.parent, base.parent)
    assert res.rounds == base.rounds
