"""Mesh direction parity (ISSUE 11 satellite): the sharded relay's
direction-optimizing schedule must be BIT-IDENTICAL to the single-chip
relay engine's for the same graph and thresholds.

Why this must hold: the Beamer predicate (models/direction.py
``take_pull`` — one definition, compiled by every program) is a pure
function of (frontier occupancy, frontier out-edge mass, unexplored
mass, real V, alpha, beta).  All four masses are layout-independent graph
quantities — the single-chip program now feeds the REAL vertex count
(not its padded vr) and both sides clamp the push budgets the same way —
so the mesh program and the single-chip program make the same decision
at every superstep, on any mesh factorization.  (Masses are float32 sums
of small integers on these fixtures — exact below 2^24 — so there is no
rounding escape hatch; the schedules must match to the last superstep.)
"""

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.graph.relay import build_sharded_relay_graph
from bfs_tpu.models.bfs import RelayEngine
from bfs_tpu.oracle.bfs import canonical_bfs, queue_bfs
from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

pytestmark = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


@pytest.fixture(scope="module")
def switchy():
    """(graph, hub source, single-chip auto/push schedules + oracle).
    The G(n,m) ramp fixture from the direction suite: sparse start, dense
    middle, sparse tail — the Beamer predicate actually switches.  The
    single-chip engine runs ONCE per mode for the whole module."""
    g = gnm_graph(1 << 10, 3 << 10, seed=5)
    deg = np.bincount(np.asarray(g.src), minlength=g.num_vertices)
    s = int(np.argmax(deg))
    d, _ = queue_bfs(g, s)
    _, p = canonical_bfs(g, s)
    sched = {}
    for mode in ("auto", "push"):
        eng = RelayEngine(g, sparse_hybrid=True, direction=mode)
        curve = eng.run_level_curve(s)
        sched[mode] = curve["direction_schedule"]["schedule"]
    # the fixture must actually exercise both bodies, or parity proves
    # nothing
    assert {"push", "pull"} <= set(sched["auto"]), sched["auto"]
    return g, s, d, p, sched


@pytest.mark.parametrize("num_shards", [2, 8])
def test_auto_schedule_parity(switchy, num_shards):
    g, s, d, p, sched = switchy
    srg = build_sharded_relay_graph(g, num_shards)
    mesh = make_mesh(graph=num_shards)
    res, curve = bfs_sharded(
        srg, s, mesh=mesh, engine="relay", telemetry=True, direction="auto"
    )
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert curve["direction_schedule"]["schedule"] == sched["auto"]


def test_push_schedule_parity_x2(switchy):
    """Forced push: the mesh's per-superstep budget dispatch must replay
    the single-chip nested-while hybrid's decisions exactly (push
    wherever the static budgets allow, dense otherwise)."""
    g, s, d, p, sched = switchy
    srg = build_sharded_relay_graph(g, 2)
    mesh = make_mesh(graph=2)
    res, curve = bfs_sharded(
        srg, s, mesh=mesh, engine="relay", telemetry=True, direction="push"
    )
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert curve["direction_schedule"]["schedule"] == sched["push"]


@pytest.mark.slow
def test_push_and_pull_end_to_end_x8(switchy):
    """The acceptance line, run explicitly: forced push AND forced pull
    end-to-end on the x8 mesh, bit-exact vs the oracle either way.  (The
    tier-1 x8 auto-parity test already executes BOTH bodies on the x8
    mesh through its switching schedule; this is the forced-mode sweep.)
    """
    g, s, d, p, _ = switchy
    srg = build_sharded_relay_graph(g, 8)
    mesh = make_mesh(graph=8)
    for mode in ("push", "pull"):
        res = bfs_sharded(srg, s, mesh=mesh, engine="relay", direction=mode)
        np.testing.assert_array_equal(res.dist, d)
        np.testing.assert_array_equal(res.parent, p)
