"""Direction-optimizing supersteps (ISSUE 7): oracle parity under every
schedule, real auto switching, packed-cap fallback under switching, the
per-phase Pallas kernels' bit-exactness, and the knob surface.

Fixture shapes: a STAR (shallow — 2 levels, hub explosion), a PATH deeper
than the packed 62-level cap (the fallback-under-switching case), and a
G(n,m) whose ramp-up/dense-middle/sparse-tail profile makes the Beamer
predicate actually switch push -> pull -> push."""

import os

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.direction import (
    DirectionConfig,
    bfs_direction,
    bfs_multi_direction,
    resolve_direction,
)
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs

needs_native = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


def star_graph(n: int = 256) -> Graph:
    """Hub 0 -> every leaf, plus the reverse edges: 2 levels from any
    leaf, 1 from the hub — the shallow extreme."""
    hub = np.zeros(n - 1, np.int32)
    leaves = np.arange(1, n, dtype=np.int32)
    src = np.concatenate([hub, leaves])
    dst = np.concatenate([leaves, hub])
    return Graph(n, src, dst)


def switchy_fixture():
    """(graph, source) whose frontier curve ramps through both Beamer
    thresholds: sparse start (push), dense middle (pull), sparse tail."""
    g = gnm_graph(1 << 10, 3 << 10, seed=5)
    deg = np.bincount(np.asarray(g.src), minlength=g.num_vertices)
    return g, int(np.argmax(deg))


def assert_oracle(g, res, s):
    d, _ = queue_bfs(g, s)
    _, p = canonical_bfs(g, s)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert check(g, res.dist, res.parent, s) == []


# ---------------------------------------------------------------------------
# Config / knob surface.
# ---------------------------------------------------------------------------

def test_resolve_direction_env_knobs(monkeypatch):
    monkeypatch.setenv("BFS_TPU_DIRECTION", "pull")
    monkeypatch.setenv("BFS_TPU_DIRECTION_ALPHA", "7.5")
    monkeypatch.setenv("BFS_TPU_DIRECTION_BETA", "48")
    cfg = resolve_direction()
    assert (cfg.mode, cfg.alpha, cfg.beta) == ("pull", 7.5, 48.0)
    # explicit argument wins over the env
    assert resolve_direction("push").mode == "push"


def test_resolve_direction_rejects_bad_knobs(monkeypatch):
    monkeypatch.setenv("BFS_TPU_DIRECTION", "sideways")
    with pytest.raises(ValueError):
        resolve_direction()
    monkeypatch.setenv("BFS_TPU_DIRECTION", "auto")
    monkeypatch.setenv("BFS_TPU_DIRECTION_ALPHA", "-1")
    with pytest.raises(ValueError):
        resolve_direction()


# ---------------------------------------------------------------------------
# Combined push/pull engine pair (models/direction.py fused program).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["auto", "push", "pull"])
def test_direction_oracle_parity_all_modes(mode):
    g, s = switchy_fixture()
    res, sched = bfs_direction(g, s, config=DirectionConfig(mode=mode))
    assert_oracle(g, res, s)
    assert sched["mode"] == mode
    if mode == "push":
        assert set(sched["schedule"]) == {"push"}
    if mode == "pull":
        assert set(sched["schedule"]) == {"pull"}


def test_direction_auto_actually_switches():
    """The acceptance shape: the auto schedule must contain BOTH
    directions and at least one switch, with parents still canonical."""
    g, s = switchy_fixture()
    res, sched = bfs_direction(g, s, config=DirectionConfig())
    assert_oracle(g, res, s)
    assert "push" in sched["schedule"] and "pull" in sched["schedule"]
    assert sched["switches"] >= 1
    # classic Beamer hysteresis: the dense middle is pull, both tails push
    assert sched["schedule"][0] == "push"


def test_direction_star_shallow():
    g = star_graph()
    res, sched = bfs_direction(g, 5, config=DirectionConfig())
    assert_oracle(g, res, 5)
    # leaf source: hub at L1 (tiny frontier, push), every other leaf at
    # L2 (the hub's mass crossed the threshold -> pull), final empty step
    assert len(sched["schedule"]) == res.num_levels
    assert sched["schedule"][0] == "push"


def test_direction_deep_path_packed_fallback():
    """Deeper than the packed 62-level cap: the fused-word carry detects
    the cap exit and re-runs unpacked UNDER the same switching — the
    schedule covers all levels and parity holds."""
    g = path_graph(80)
    res, sched = bfs_direction(g, 0, config=DirectionConfig())
    assert_oracle(g, res, 0)
    assert res.num_levels == 80
    assert len(sched["schedule"]) == 80


def test_direction_multi_source_parity():
    from bfs_tpu.models.multisource import bfs_multi

    g, s = switchy_fixture()
    sources = [s, 3, 11]
    res, sched = bfs_multi_direction(g, sources, config=DirectionConfig())
    ref = bfs_multi(g, sources)
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)
    assert len(sched["schedule"]) >= 1


def test_direction_thresholds_move_the_switch():
    """alpha/beta are live knobs (pull when ``m_f * alpha > m_u``): a
    vanishing alpha never satisfies the pull condition — all push; an
    enormous alpha satisfies it immediately — pull from level 1."""
    g, s = switchy_fixture()
    _, push_heavy = bfs_direction(
        g, s, config=DirectionConfig(mode="auto", alpha=1e-9, beta=1e9)
    )
    # Every non-terminal superstep pushes; the terminal one may pull —
    # with the whole component explored m_u == 0, so ANY positive
    # frontier mass satisfies the pull condition (classic Beamer does
    # the same at the boundary).
    assert set(push_heavy["schedule"][:-1]) == {"push"}
    _, pull_heavy = bfs_direction(
        g, s, config=DirectionConfig(mode="auto", alpha=1e9, beta=1e9)
    )
    assert "pull" in pull_heavy["schedule"]
    assert pull_heavy["schedule"][0] == "pull"


# ---------------------------------------------------------------------------
# Relay engine switching (models/bfs.py fused program).
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.parametrize("mode", ["auto", "push", "pull"])
def test_relay_direction_oracle_parity(mode):
    from bfs_tpu.models.bfs import RelayEngine

    g, s = switchy_fixture()
    eng = RelayEngine(g, sparse_hybrid=True, direction=mode)
    res = eng.run(s)
    assert_oracle(g, res, s)
    curve = eng.run_level_curve(s)
    sched = curve["direction_schedule"]
    assert sched["mode"] == mode
    if mode == "auto":
        assert "push" in sched["schedule"] and "pull" in sched["schedule"]
        assert sched["switches"] >= 1
    elif mode == "pull":
        assert set(sched["schedule"]) == {"pull"}
    else:
        assert set(sched["schedule"]) == {"push"}


@needs_native
def test_relay_direction_auto_without_hybrid_is_pull():
    """No sparse adjacency shipped -> auto degenerates to dense-only and
    the schedule says so (never a silently-wrong sparse body)."""
    from bfs_tpu.models.bfs import RelayEngine

    g, s = switchy_fixture()
    eng = RelayEngine(g, sparse_hybrid=False, direction="auto")
    res = eng.run(s)
    assert_oracle(g, res, s)
    sched = eng.run_level_curve(s)["direction_schedule"]
    assert set(sched["schedule"]) == {"pull"}


@needs_native
def test_relay_direction_deep_path_packed_fallback():
    from bfs_tpu.models.bfs import RelayEngine

    g = path_graph(80)
    eng = RelayEngine(g, sparse_hybrid=True, direction="auto")
    res = eng.run(0)
    assert_oracle(g, res, 0)
    assert res.num_levels == 80
    curve = eng.run_level_curve(0)
    assert len(curve["direction_schedule"]["schedule"]) == 80


@needs_native
def test_relay_schedule_deterministic_across_engines():
    """The journal-replay invariant's core: the schedule is a pure
    function of graph + thresholds — two engines on the same graph
    produce identical schedules."""
    from bfs_tpu.models.bfs import RelayEngine

    g, s = switchy_fixture()
    s1 = RelayEngine(g, sparse_hybrid=True, direction="auto")
    s2 = RelayEngine(g, sparse_hybrid=True, direction="auto")
    a = s1.run_level_curve(s)["direction_schedule"]["schedule"]
    b = s2.run_level_curve(s)["direction_schedule"]["schedule"]
    assert a == b


# ---------------------------------------------------------------------------
# Per-phase Pallas kernels (ops/relay_pallas.py, interpret mode on CPU).
# ---------------------------------------------------------------------------

@needs_native
def test_pallas_rowmin_and_update_bit_exact():
    """The fused tournament and packed-min kernels vs their XLA twins,
    superstep by superstep on a real relay layout."""
    import jax.numpy as jnp

    from bfs_tpu.graph.relay import valid_slot_words
    from bfs_tpu.models.bfs import RelayEngine
    from bfs_tpu.ops import relay as R
    from bfs_tpu.ops import relay_pallas as RP

    g = rmat_graph(9, 8, seed=11)
    eng = RelayEngine(g, sparse_hybrid=False)
    rg = eng.relay_graph
    vr = rg.vr
    st = eng.init_packed_state(3)
    valid = jnp.asarray(valid_slot_words(rg.src_l1, rg.net_size))
    vm, nm = jnp.asarray(rg.vperm_masks), jnp.asarray(rg.net_masks)
    assert any(RP.rowmin_class_ok(cs) for cs in rg.in_classes), (
        "no class on the fused tournament — the kernel is not exercised"
    )
    for _ in range(3):
        fw = jnp.concatenate(
            [st.fwords, jnp.zeros((rg.vperm_size - vr) // 32, jnp.uint32)]
        )
        y = R.apply_benes_std(fw, vm, rg.vperm_table, rg.vperm_size)
        l2 = R.broadcast_l2(y, rg.out_classes, rg.net_size, rg.out_space)
        l1 = R.apply_benes_std(l2, nm, rg.net_table, rg.net_size)
        ref = R.rowmin_ranks(l1, valid, rg.in_classes, vr)
        got = RP.rowmin_ranks_pallas(
            l1, valid, rg.in_classes, vr, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        s_ref = R.apply_relay_candidates_packed(st, ref)
        s_got = RP.apply_relay_candidates_packed_pallas(
            st, got, interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(s_ref.packed), np.asarray(s_got.packed)
        )
        np.testing.assert_array_equal(
            np.asarray(s_ref.fwords), np.asarray(s_got.fwords)
        )
        assert bool(s_ref.changed) == bool(s_got.changed)
        st = s_ref


@needs_native
def test_forced_pallas_phases_end_to_end(monkeypatch):
    """BFS_TPU_ROWMIN/BFS_TPU_STATE_UPDATE=pallas force the fused kernels
    into the production superstep (interpret mode here) — full searches
    stay oracle-exact, and the selection records the forced basis."""
    from bfs_tpu.models.bfs import RelayEngine

    monkeypatch.setenv("BFS_TPU_ROWMIN", "pallas")
    monkeypatch.setenv("BFS_TPU_STATE_UPDATE", "pallas")
    g, s = switchy_fixture()
    eng = RelayEngine(g, sparse_hybrid=True, direction="auto")
    assert eng.phase_selection["rowmin"] == "pallas"
    assert eng.phase_selection["basis"]["rowmin"] == "forced (env)"
    res = eng.run(s)
    assert_oracle(g, res, s)


@needs_native
def test_phase_selection_defaults_to_measured_xla_off_tpu():
    from bfs_tpu.models.bfs import RelayEngine

    g, _ = switchy_fixture()
    eng = RelayEngine(g, sparse_hybrid=False)
    assert eng.phase_selection["rowmin"] == "xla"
    assert "interpret" in eng.phase_selection["basis"]["rowmin"] or (
        "non-tpu" in eng.phase_selection["basis"]["rowmin"]
    )


@needs_native
def test_phase_probe_measures_both_arms():
    """probe_phase_kernels returns a real two-arm comparison for both
    phases — selection_basis is always a measurement."""
    from bfs_tpu.models.bfs import RelayEngine
    from bfs_tpu.profiling import probe_phase_kernels

    g = rmat_graph(9, 8, seed=11)
    eng = RelayEngine(g, sparse_hybrid=False)
    probe = probe_phase_kernels(eng, loops=2, repeats=2)
    for phase in ("rowmin", "state_update"):
        rec = probe[phase]
        assert "xla_seconds" in rec
        assert "pallas_seconds" in rec or "pallas_error" in rec
        assert rec["selected"] in ("xla", "pallas")
        assert rec["selection_basis"].startswith("measured")


def test_pallas_kernels_carry_hot_pragmas():
    """Pin: the new kernels (and the direction predicate) are declared
    hot — deleting a pragma fails here, not silently in review."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from bfs_tpu.analysis.core import SourceFile, hot_regions

    for rel, names in (
        ("bfs_tpu/ops/relay_pallas.py",
         ("rowmin_ranks_pallas", "apply_relay_candidates_packed_pallas")),
        ("bfs_tpu/models/direction.py", ("take_pull", "frontier_masses")),
        ("bfs_tpu/obs/telemetry.py", ("record_direction",
                                      "record_exchange")),
        ("bfs_tpu/parallel/exchange.py", ("exchange_flat",
                                          "exchange_bitmap",
                                          "exchange_delta")),
    ):
        src = SourceFile(os.path.join(repo, rel), repo)
        declared = {r.name for r in hot_regions(src)}
        for n in names:
            assert n in declared, (rel, n, sorted(declared))


# ---------------------------------------------------------------------------
# Sharded surface.
# ---------------------------------------------------------------------------

@needs_native
def test_sharded_direction_push_runs_and_schedule_ships():
    """The ISSUE 11 satellite: the per-shard adjacency landed, so
    ``direction='push'`` no longer raises on the mesh — every mode runs
    end-to-end and the schedule ships with the curve.  (The bit-identical
    mesh-vs-single-chip schedule parity lives in
    tests/test_direction_sharded.py.)"""
    from dataclasses import replace

    from bfs_tpu.graph.relay import build_sharded_relay_graph
    from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

    g = rmat_graph(9, 8, seed=11)
    mesh = make_mesh(graph=2)
    srg = build_sharded_relay_graph(g, 2)
    res = bfs_sharded(srg, 0, mesh=mesh, engine="relay", direction="push")
    assert_oracle(g, res, 0)
    res, curve = bfs_sharded(
        srg, 0, mesh=mesh, engine="relay", telemetry=True, direction="auto"
    )
    assert_oracle(g, res, 0)
    sched = curve["direction_schedule"]
    assert sched["schedule"], "schedule must cover the executed levels"
    assert set(sched["schedule"]) <= {"push", "pull"}
    # A layout built WITHOUT the adjacency still rejects push (and its
    # auto flavor compiles the dense-only body — the pre-exchange
    # fallback contract; the program-level normalization is asserted in
    # the sharded program's docstring/IR specs without paying another
    # compile here).
    bare = replace(
        srg, adj_indptr=None, adj_dst=None, adj_slot=None, outdeg=None,
    )
    with pytest.raises(ValueError, match="adjacency"):
        bfs_sharded(bare, 0, mesh=mesh, engine="relay", direction="push")
