"""Checkpoint/resume and config-layer tests (SURVEY.md §5 rows
checkpoint/resume + config; ServiceConfiguration.java:30-63 parity)."""

import numpy as np
import pytest

from bfs_tpu.config import ServiceConfiguration, parse_properties
from bfs_tpu.graph.generators import gnm_graph
from bfs_tpu.graph.vertex import parse_state, serialize_state
from bfs_tpu.models.bfs import SuperstepRunner, bfs
from bfs_tpu.utils.checkpoint import load_checkpoint, save_checkpoint, state_from_arrays
from bfs_tpu.utils.metrics import RunMetrics
from bfs_tpu.utils.timing import Stopwatch


def test_npz_checkpoint_resume(tmp_path):
    g = gnm_graph(120, 360, seed=4)
    runner = SuperstepRunner(g)
    state = runner.init(0)
    state = runner.step(state)
    state = runner.step(state)
    ckpt = tmp_path / "step2.npz"
    save_checkpoint(ckpt, state)

    resumed = load_checkpoint(ckpt)
    assert int(resumed.level) == 2
    while bool(resumed.changed):
        resumed = runner.step(resumed)

    full = bfs(g, 0)
    np.testing.assert_array_equal(np.asarray(resumed.dist[:120]), full.dist)
    np.testing.assert_array_equal(np.asarray(resumed.parent[:120]), full.parent)


def test_text_dump_resume(tiny_graph):
    # Resume from a problemFile_i-style text dump: the reference's de-facto
    # checkpoint mechanism (BfsSpark.java:62,115-116).
    runner = SuperstepRunner(tiny_graph)
    state = runner.step(runner.init(0))
    text = serialize_state(tiny_graph, state.dist, state.parent, state.frontier)
    dist, parent, frontier = parse_state(text, 6)
    resumed = state_from_arrays(dist, parent, frontier, level=int(state.level))
    while bool(resumed.changed):
        resumed = runner.step(resumed)
    full = bfs(tiny_graph, 0)
    np.testing.assert_array_equal(np.asarray(resumed.dist[:6]), full.dist)
    np.testing.assert_array_equal(np.asarray(resumed.parent[:6]), full.parent)


def test_parse_properties():
    props = parse_properties(
        "# comment\napp-name = X\nproblemFiles = a.txt, b.txt\n\n! bang comment\n"
    )
    assert props == {"app-name": "X", "problemFiles": "a.txt, b.txt"}
    with pytest.raises(ValueError):
        parse_properties("no equals sign here")


def test_service_configuration_load(tmp_path):
    p = tmp_path / "service.properties"
    p.write_text(
        "app-name = BFS TPU\nproblemFiles = tiny.txt, medium.txt\n"
        "source = 2\nmesh-graph = 4\ndump-supersteps = true\n"
    )
    cfg = ServiceConfiguration.load(p)
    assert cfg.app_name == "BFS TPU"
    assert cfg.problem_files == ("tiny.txt", "medium.txt")
    assert cfg.source == 2 and cfg.mesh_graph == 4 and cfg.dump_supersteps


def test_config_missing_file_raises():
    # Deliberate divergence: the reference swallows config errors into null
    # getters (ServiceConfiguration.java:40-42); we fail fast.
    with pytest.raises(OSError):
        ServiceConfiguration.load("/nonexistent/service.properties")


def test_metrics_teps():
    m = RunMetrics(num_vertices=10, num_edges=1000)
    m.record(1, 5, 0.001)
    m.record(2, 0, 0.001)
    assert m.total_seconds == pytest.approx(0.002)
    assert m.teps() == pytest.approx(500_000)
    assert m.num_levels == 2
    assert any("Elapsed time [1]" in line for line in m.log_lines())


def test_stopwatch():
    sw = Stopwatch.create_started()
    assert sw.running
    sw.stop()
    t1 = sw.elapsed_s
    sw.start()
    sw.stop()
    assert sw.elapsed_s >= t1
    with pytest.raises(RuntimeError):
        sw.stop()
