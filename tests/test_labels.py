"""Landmark distance-label oracle tier tests (ISSUE 20).

Covers: deterministic degree-weighted landmark sampling, the tightness
certificate (every tight answer bit-exact vs the host oracle; every
non-tight pair falls back to the exact traversal — star leaves, path
ends, gnm and R-MAT pairs), certified-disconnected pairs, exact path
reconstruction through the certifying landmark, the sidecar cache
round-trip + corruption rebuild, the budget gate, serve-tier epoch-swap
invalidation, sampled verification, and kill/resume of the chunked
precompute through the superstep-checkpoint store (bit-identical to an
uninterrupted build).
"""

import os

import numpy as np
import pytest

from bfs_tpu.cache.layout import (
    LayoutCache,
    graph_content_hash,
    labels_key,
    load_or_build_labels,
    verify_labels_bundle,
)
from bfs_tpu.graph.csr import Graph, INF_DIST
from bfs_tpu.graph.generators import (
    gnm_graph,
    path_graph,
    rmat_graph,
    star_graph,
)
from bfs_tpu.oracle.bfs import queue_bfs
from bfs_tpu.resilience import faults
from bfs_tpu.resilience.faults import FaultInjected
from bfs_tpu.serve import BfsServer, LabelBudgetError, LabelOracle
from bfs_tpu.serve.labels import (
    LABEL_INF,
    build_label_index,
    sample_landmarks,
)

TIMEOUT = 300

GRAPHS = {
    "star": lambda: star_graph(40),
    "path": lambda: path_graph(33),
    "gnm": lambda: gnm_graph(150, 400, seed=11),
    "rmat": lambda: rmat_graph(7, 4, seed=5),
}


def _pairs(graph, n, seed=0):
    rng = np.random.default_rng(seed)
    v = graph.num_vertices
    return (
        rng.integers(0, v, size=n).astype(np.int32),
        rng.integers(0, v, size=n).astype(np.int32),
    )


def _truth(graph, cache, u):
    if u not in cache:
        cache[u] = queue_bfs(graph, int(u))[0]
    return cache[u]


# ------------------------------------------------------------- sampling --

def test_landmarks_deterministic_and_in_range():
    g = GRAPHS["gnm"]()
    a = sample_landmarks(g, 8)
    b = sample_landmarks(g, 8)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int32 and a.shape == (8,)
    assert len(set(a.tolist())) == 8
    deg = np.bincount(np.asarray(g.src), minlength=g.num_vertices)
    assert (0 <= a).all() and (a < g.num_vertices).all()
    assert (deg[a] > 0).all()  # zero-degree vertices are never landmarks


def test_landmarks_clamped_to_usable_roots():
    g = path_graph(5)
    lm = sample_landmarks(g, 64)
    assert lm.shape[0] == 5  # clamped: only 5 usable roots exist


# -------------------------------------------- certificate vs host oracle --

@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_tight_answers_match_host_oracle(name):
    g = GRAPHS[name]()
    oracle = LabelOracle(build_label_index(g, 6))
    us, vs = _pairs(g, 300, seed=3)
    d, tight, _, upper, lower = oracle.bounds(us, vs)
    cache = {}
    for u, v, du, t, up, lo in zip(us, vs, d, tight, upper, lower):
        want = int(_truth(g, cache, int(u))[v])
        if t:
            assert int(du) == want, f"tight answer wrong for ({u},{v})"
        if want < INF_DIST:
            # The bounds must sandwich the true distance on every
            # connected pair, tight or not.
            assert int(lo) <= want <= int(up)


def test_star_leaf_pairs_never_tight_but_served_exactly():
    """The adversarial shape: every leaf-leaf pair has upper=2, lower=0 —
    the certificate must refuse them all, and the serve tier must answer
    them exactly through the fallback traversal."""
    g = GRAPHS["star"]()
    idx = build_label_index(g, 4)
    oracle = LabelOracle(idx)
    # A leaf that IS a landmark makes its own pairs legitimately tight
    # (d(L, u) = 0 collapses the sandwich) — the adversarial pairs are
    # the leaf-leaf pairs with NO landmark endpoint.
    lm = set(idx.landmarks.tolist())
    leaves = np.asarray(
        [x for x in range(1, g.num_vertices) if x not in lm], dtype=np.int32
    )
    us, vs = leaves[:-1], leaves[1:]
    d, tight, _ = oracle.dist(us, vs)
    assert not tight.any()
    assert (d >= 2).all()  # upper bound, never below the true distance


def test_disconnected_pairs_certified_exact():
    # Two separate paths: any landmark reaching exactly one side
    # certifies cross-pairs disconnected (exact INF_DIST, tight).
    edges = np.array([[0, 1], [1, 2], [3, 4], [4, 5]], dtype=np.int32)
    g = Graph.from_undirected_edges(6, edges)
    oracle = LabelOracle(build_label_index(g, 6))
    d, tight, _ = oracle.dist([0, 2, 1], [3, 5, 4])
    assert tight.all()
    assert (d == INF_DIST).all()


def test_path_reconstruction_is_exact_shortest_path():
    g = GRAPHS["gnm"]()
    oracle = LabelOracle(build_label_index(g, 8))
    edge_set = {
        (int(a), int(b)) for a, b in zip(g.src, g.dst)
    }
    us, vs = _pairs(g, 200, seed=7)
    d, tight, _ = oracle.dist(us, vs)
    cache = {}
    checked = 0
    for u, v, t in zip(us, vs, tight):
        if not t:
            continue
        path = oracle.path(int(u), int(v))
        want = int(_truth(g, cache, int(u))[v])
        if want >= INF_DIST:
            assert path is None or len(path) == 1
            continue
        assert path is not None
        assert path[0] == int(u) and path[-1] == int(v)
        assert len(path) == want + 1  # a SHORTEST path, not just a walk
        for a, b in zip(path, path[1:]):
            assert (a, b) in edge_set
        checked += 1
    assert checked  # the certificate fired on at least one connected pair


# ------------------------------------------------------- sidecar bundle --

def test_sidecar_roundtrip_corruption_and_verify(tmp_path):
    g = GRAPHS["gnm"]()
    cache = LayoutCache(tmp_path)
    key = labels_key(g, 5)

    absent = verify_labels_bundle(g, 5, cache=cache)
    assert not absent["ok"] and absent["status"] == "absent"

    idx, info = load_or_build_labels(g, 5, cache=cache)
    assert info["cache"] == "miss" and info["key"] == key
    idx2, info2 = load_or_build_labels(g, 5, cache=cache)
    assert info2["cache"] == "hit"
    np.testing.assert_array_equal(idx.dist, idx2.dist)
    np.testing.assert_array_equal(idx.parent, idx2.parent)
    np.testing.assert_array_equal(idx.landmarks, idx2.landmarks)

    verdict = verify_labels_bundle(g, 5, cache=cache)
    assert verdict["ok"] and verdict["status"] == "ok"
    assert verdict["k"] == 5
    assert verdict["device_bytes"] == idx.device_bytes
    assert verdict["index_bytes"] == idx.nbytes

    # Corrupt the stored dist rows: the fingerprint check must drop the
    # bundle (verify -> absent) and the next load must REBUILD, not trust.
    bundle_dir = os.path.join(str(tmp_path), key)
    target = max(
        (os.path.join(bundle_dir, f) for f in os.listdir(bundle_dir)),
        key=os.path.getsize,
    )
    with open(target, "r+b") as f:
        f.seek(0)
        f.write(b"\xff" * 64)
    assert not verify_labels_bundle(g, 5, cache=cache)["ok"]
    idx3, info3 = load_or_build_labels(g, 5, cache=cache)
    assert info3["cache"] == "miss"
    np.testing.assert_array_equal(idx.dist, idx3.dist)


def test_budget_gate_rejects_oversized_index():
    g = GRAPHS["gnm"]()
    idx = build_label_index(g, 4)
    with pytest.raises(LabelBudgetError):
        LabelOracle(idx, budget_bytes=idx.device_bytes - 1)
    LabelOracle(idx, budget_bytes=idx.device_bytes)  # exactly at budget: ok


# ------------------------------------------------- kill/resume precompute --

@pytest.mark.chaos
def test_precompute_kill_resume_bit_identical(tmp_path):
    from bfs_tpu.resilience.superstep_ckpt import SuperstepCheckpointer

    g = GRAPHS["gnm"]()
    golden = build_label_index(g, 4, chunk=1, ckpt_dir=tmp_path / "golden")

    os.environ["BFS_TPU_CKPT"] = "every:1"
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            build_label_index(g, 4, chunk=1, ckpt_dir=tmp_path / "ck")
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()

    try:
        # The fault fired AFTER epoch 2's durability: the store must
        # resume the sweep at chunk 2, not restart it.
        ck = SuperstepCheckpointer(tmp_path / "ck", {
            "kind": "labels", "graph": graph_content_hash(g), "k": 4,
            "engine": "pull", "chunk": 1,
        })
        found = ck.load_latest()
        assert found is not None and int(found[0]) == 2
        resumed = build_label_index(g, 4, chunk=1, ckpt_dir=tmp_path / "ck")
    finally:
        os.environ.pop("BFS_TPU_CKPT", None)
    np.testing.assert_array_equal(resumed.dist, golden.dist)
    np.testing.assert_array_equal(resumed.parent, golden.parent)
    np.testing.assert_array_equal(resumed.landmarks, golden.landmarks)
    assert (resumed.dist != LABEL_INF).any()


# ------------------------------------------------------------ serve tier --

def _label_server(graph, k, tmp_path=None, **kw):
    os.environ["BFS_TPU_LABELS"] = str(k)
    try:
        srv = BfsServer(max_batch=8, **kw)
        srv.register("g", graph)
    finally:
        os.environ.pop("BFS_TPU_LABELS", None)
    return srv


@pytest.mark.parametrize("name", sorted(GRAPHS))
def test_server_point_queries_exact_with_fallback(name):
    """Every query_dist answer — label-tier hit or traversal fallback —
    must equal the host oracle, and the hit/fallback counters must
    account for every query."""
    g = GRAPHS[name]()
    with _label_server(g, 6) as srv:
        us, vs = _pairs(g, 25, seed=13)
        cache = {}
        for u, v in zip(us, vs):
            reply = srv.query_dist("g", int(u), int(v)).result(TIMEOUT)
            want = int(_truth(g, cache, int(u))[v])
            assert reply.dist == want, (
                f"dist({u},{v}) = {reply.dist} via {reply.method}, "
                f"oracle says {want}"
            )
            assert reply.method in ("labels", "exact", "labels_verified")
        c = srv.metrics.report()["counters"]
        assert c.get("label_hits", 0) + c.get("label_fallbacks", 0) == 25
        assert c.get("label_builds", 0) == 1


def test_server_star_fallback_on_every_leaf_pair():
    g = GRAPHS["star"]()
    lm = set(sample_landmarks(g, 4).tolist())
    leaves = [x for x in range(1, g.num_vertices) if x not in lm]
    pairs = list(zip(leaves[0::2], leaves[1::2]))[:4]
    with _label_server(g, 4) as srv:
        cache = {}
        for u, v in pairs:
            reply = srv.query_dist("g", u, v).result(TIMEOUT)
            assert reply.method == "exact"  # never tight on these pairs
            assert reply.dist == int(_truth(g, cache, u)[v]) == 2
        c = srv.metrics.report()["counters"]
        assert c.get("label_fallbacks", 0) == len(pairs)
        assert c.get("label_hits", 0) == 0


def test_server_sampled_verification_clean():
    g = GRAPHS["gnm"]()
    os.environ["BFS_TPU_LABELS_VERIFY"] = "2"
    try:
        with _label_server(g, 8) as srv:
            us, vs = _pairs(g, 30, seed=5)
            cache = {}
            for u, v in zip(us, vs):
                reply = srv.query_dist("g", int(u), int(v)).result(TIMEOUT)
                assert reply.dist == int(_truth(g, cache, int(u))[v])
            c = srv.metrics.report()["counters"]
            if c.get("label_hits", 0) >= 2:
                assert c.get("label_verifies", 0) >= 1
            assert c.get("label_verify_failures", 0) == 0
    finally:
        os.environ.pop("BFS_TPU_LABELS_VERIFY", None)


def test_epoch_swap_invalidates_and_rebuilds():
    g = GRAPHS["gnm"]()
    with _label_server(g, 6) as srv:
        rec1 = srv.registry.get("g")
        assert srv._label_oracle("g", rec1.epoch) is not None
        os.environ["BFS_TPU_LABELS"] = "6"
        try:
            srv.register("g", g)  # epoch bump
        finally:
            os.environ.pop("BFS_TPU_LABELS", None)
        rec2 = srv.registry.get("g")
        assert rec2.epoch != rec1.epoch
        assert srv._label_oracle("g", rec1.epoch) is None  # retired
        assert srv._label_oracle("g", rec2.epoch) is not None
        reply = srv.query_dist("g", 3, 90).result(TIMEOUT)
        assert reply.dist == int(queue_bfs(g, 3)[0][90])


def test_unregister_drops_label_state():
    g = GRAPHS["gnm"]()
    with _label_server(g, 4) as srv:
        rec = srv.registry.get("g")
        srv.unregister("g")
        assert srv._label_oracle("g", rec.epoch) is None


def test_budget_reject_keeps_serving_exact():
    g = GRAPHS["gnm"]()
    os.environ["BFS_TPU_LABELS_GB"] = "0.0000001"  # ~100 bytes
    try:
        with _label_server(g, 6) as srv:
            c = srv.metrics.report()["counters"]
            assert c.get("label_budget_rejects", 0) == 1
            reply = srv.query_dist("g", 3, 90).result(TIMEOUT)
            assert reply.method == "exact"
            assert reply.dist == int(queue_bfs(g, 3)[0][90])
            assert srv.metrics.report()["counters"].get("label_misses", 0) == 1
    finally:
        os.environ.pop("BFS_TPU_LABELS_GB", None)


def test_labels_off_serves_exact_only():
    g = GRAPHS["gnm"]()
    with BfsServer(max_batch=8) as srv:  # BFS_TPU_LABELS defaults off
        srv.register("g", g)
        reply = srv.query_dist("g", 0, 1).result(TIMEOUT)
        assert reply.method == "exact"
        assert reply.dist == int(queue_bfs(g, 0)[0][1])
        assert srv.metrics.report()["counters"].get("label_builds", 0) == 0
