"""Native data-loader (native/graph_gen.cpp) vs the NumPy fallbacks."""

import numpy as np
import pytest

from bfs_tpu.graph import native_gen
from bfs_tpu.graph.io import read_sedgewick
from conftest import TINY_TEXT

pytestmark = pytest.mark.skipif(
    not native_gen.native_available(), reason="native graph_gen unavailable"
)


def test_rmat_native_shape_range_determinism():
    s1, d1 = native_gen.rmat_edges_native(8, 4, seed=7)
    s2, d2 = native_gen.rmat_edges_native(8, 4, seed=7)
    assert s1.shape == d1.shape == (4 * 256,)
    assert s1.min() >= 0 and s1.max() < 256
    assert d1.min() >= 0 and d1.max() < 256
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(d1, d2)
    s3, _ = native_gen.rmat_edges_native(8, 4, seed=8)
    assert not np.array_equal(s1, s3)


def test_rmat_native_skew():
    # R-MAT graphs are skewed: max degree far above the mean.
    src, dst = native_gen.rmat_edges_native(10, 16, seed=1)
    deg = np.bincount(src, minlength=1 << 10) + np.bincount(dst, minlength=1 << 10)
    assert deg.max() > 8 * deg.mean()


def test_sort_edges_matches_lexsort():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 1000, size=20_000).astype(np.int32)
    dst = rng.integers(0, 1000, size=20_000).astype(np.int32)
    order = np.lexsort((src, dst))
    want_src, want_dst = src[order], dst[order]
    got_src, got_dst = native_gen.sort_edges_by_dst_native(src.copy(), dst.copy())
    np.testing.assert_array_equal(got_src, want_src)
    np.testing.assert_array_equal(got_dst, want_dst)


def test_sedgewick_native_matches_python(tmp_path):
    path = tmp_path / "tiny.txt"
    path.write_text(TINY_TEXT)
    v, src, dst = native_gen.read_sedgewick_native(str(path))
    graph = read_sedgewick(str(path))
    assert v == graph.num_vertices
    # Python reader bi-directs; native returns the raw undirected pairs.
    assert 2 * src.shape[0] == graph.num_edges
    np.testing.assert_array_equal(
        np.sort(np.concatenate([src, dst])), np.sort(np.concatenate([graph.src]))
    )


def test_sedgewick_native_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.txt"
    bad.write_text("6\n8\n0 5\n")  # promises 8 edges, has 1
    with pytest.raises(ValueError):
        native_gen.read_sedgewick_native(str(bad))


def test_rank_by_count_matches_stable_sort_ranks():
    rng = np.random.default_rng(11)
    key = rng.integers(0, 50, 3000).astype(np.int32)
    rank = native_gen.rank_by_count_native(key, 50)
    # rank[i] = number of earlier records with the same key
    want = np.zeros_like(rank)
    seen = {}
    for i, k in enumerate(key.tolist()):
        want[i] = seen.get(k, 0)
        seen[k] = want[i] + 1
    np.testing.assert_array_equal(rank, want)


def test_csr_fill_groups_by_key():
    rng = np.random.default_rng(12)
    n, nk = 5000, 200
    srcn = rng.integers(0, nk, n).astype(np.int32)
    dstn = rng.integers(0, 10_000, n).astype(np.int32)
    slotv = np.arange(n, dtype=np.int32)
    indptr, adj_dst, adj_slot = native_gen.csr_fill_native(srcn, dstn, slotv, nk)
    assert indptr.shape == (nk + 2,)
    assert indptr[nk] == indptr[nk + 1] == n
    for k in range(nk):
        sl = slice(int(indptr[k]), int(indptr[k + 1]))
        # every edge in row k really has key k, and the row is complete
        np.testing.assert_array_equal(srcn[adj_slot[sl]], k)
        np.testing.assert_array_equal(
            np.sort(adj_slot[sl]), np.sort(np.flatnonzero(srcn == k))
        )
        np.testing.assert_array_equal(adj_dst[sl], dstn[adj_slot[sl]])


def test_pad_identity_native_identity_first():
    rng = np.random.default_rng(13)
    n = 4096
    perm = np.full(n, -1, dtype=np.int32)
    # partial mapping: outputs 0..99 <- random distinct inputs 1000..1099
    ins = (1000 + rng.permutation(100)).astype(np.int32)
    perm[:100] = ins
    used = np.zeros(n, dtype=np.uint8)
    native_gen.mark_u8_native(ins, used)
    native_gen.pad_identity_native(perm, used)
    # bijection
    np.testing.assert_array_equal(np.sort(perm), np.arange(n))
    # identity-first: free output j with free input j must map j -> j
    for j in range(100, 1000):
        assert perm[j] == j
    for j in range(1100, n):
        assert perm[j] == j
