"""Native data-loader (native/graph_gen.cpp) vs the NumPy fallbacks."""

import numpy as np
import pytest

from bfs_tpu.graph import native_gen
from bfs_tpu.graph.io import read_sedgewick
from conftest import TINY_TEXT

pytestmark = pytest.mark.skipif(
    not native_gen.native_available(), reason="native graph_gen unavailable"
)


def test_rmat_native_shape_range_determinism():
    s1, d1 = native_gen.rmat_edges_native(8, 4, seed=7)
    s2, d2 = native_gen.rmat_edges_native(8, 4, seed=7)
    assert s1.shape == d1.shape == (4 * 256,)
    assert s1.min() >= 0 and s1.max() < 256
    assert d1.min() >= 0 and d1.max() < 256
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(d1, d2)
    s3, _ = native_gen.rmat_edges_native(8, 4, seed=8)
    assert not np.array_equal(s1, s3)


def test_rmat_native_skew():
    # R-MAT graphs are skewed: max degree far above the mean.
    src, dst = native_gen.rmat_edges_native(10, 16, seed=1)
    deg = np.bincount(src, minlength=1 << 10) + np.bincount(dst, minlength=1 << 10)
    assert deg.max() > 8 * deg.mean()


def test_sort_edges_matches_lexsort():
    rng = np.random.default_rng(0)
    src = rng.integers(0, 1000, size=20_000).astype(np.int32)
    dst = rng.integers(0, 1000, size=20_000).astype(np.int32)
    order = np.lexsort((src, dst))
    want_src, want_dst = src[order], dst[order]
    got_src, got_dst = native_gen.sort_edges_by_dst_native(src.copy(), dst.copy())
    np.testing.assert_array_equal(got_src, want_src)
    np.testing.assert_array_equal(got_dst, want_dst)


def test_sedgewick_native_matches_python(tmp_path):
    path = tmp_path / "tiny.txt"
    path.write_text(TINY_TEXT)
    v, src, dst = native_gen.read_sedgewick_native(str(path))
    graph = read_sedgewick(str(path))
    assert v == graph.num_vertices
    # Python reader bi-directs; native returns the raw undirected pairs.
    assert 2 * src.shape[0] == graph.num_edges
    np.testing.assert_array_equal(
        np.sort(np.concatenate([src, dst])), np.sort(np.concatenate([graph.src]))
    )


def test_sedgewick_native_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.txt"
    bad.write_text("6\n8\n0 5\n")  # promises 8 edges, has 1
    with pytest.raises(ValueError):
        native_gen.read_sedgewick_native(str(bad))
