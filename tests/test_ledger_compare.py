"""Edge-case coverage for tools/ledger_compare.py (ISSUE 8 satellite):
a phase missing from one capture, ``--exact`` on captures without
selected arms, and the non-zero exit codes — all asserted in-process
(the tool is stdlib-only; its ``main`` returns the exit code)."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def lc():
    spec = importlib.util.spec_from_file_location(
        "ledger_compare", os.path.join(REPO, "tools", "ledger_compare.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_ledger(path, phases, schedule=None):
    """Raw ledger JSON (the `python -m bfs_tpu.profiling` shape), or a
    bench headline when ``schedule`` is given."""
    ledger = {"phases": {k: {"seconds": v} for k, v in phases.items()}}
    if schedule is not None:
        doc = {"details": {"superstep_phases": ledger,
                           "direction_schedule": {"schedule": schedule}}}
    else:
        doc = ledger
    path.write_text(json.dumps(doc))
    return str(path)


def _run(lc, monkeypatch, argv):
    monkeypatch.setattr(sys, "argv", ["ledger_compare.py", *argv])
    return lc.main()


def test_missing_phase_tolerated_without_exact(lc, tmp_path, monkeypatch,
                                               capsys):
    before = _write_ledger(tmp_path / "b.json",
                           {"vperm": 1e-3, "rowmin": 2e-3})
    after = _write_ledger(tmp_path / "a.json", {"vperm": 1e-3})
    rc = _run(lc, monkeypatch, [before, after])
    out = capsys.readouterr().out
    assert rc == 0
    assert "rowmin" in out and "—" in out  # shown as absent, not a crash


def test_missing_phase_fails_exact(lc, tmp_path, monkeypatch, capsys):
    before = _write_ledger(tmp_path / "b.json",
                           {"vperm": 1e-3, "rowmin": 2e-3})
    after = _write_ledger(tmp_path / "a.json", {"vperm": 1e-3})
    rc = _run(lc, monkeypatch, [before, after, "--exact"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "rowmin" in err


def test_exact_without_arms_or_schedule_passes(lc, tmp_path, monkeypatch,
                                               capsys):
    # Captures with no `selected` arm annotations and no direction
    # schedule (pre-ISSUE-7 ledgers): --exact must compare what exists
    # and pass on bit-identical phases.
    phases = {"vperm": 1.25e-3, "net_apply": 3.5e-3}
    before = _write_ledger(tmp_path / "b.json", phases)
    after = _write_ledger(tmp_path / "a.json", phases)
    rc = _run(lc, monkeypatch, [before, after, "--exact"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "exact match" in captured.err
    assert "selected arms" not in captured.err


def test_exact_catches_schedule_divergence(lc, tmp_path, monkeypatch,
                                           capsys):
    phases = {"vperm": 1e-3}
    before = _write_ledger(tmp_path / "b.json", phases,
                           schedule=["push", "pull"])
    after = _write_ledger(tmp_path / "a.json", phases,
                          schedule=["pull", "pull"])
    rc = _run(lc, monkeypatch, [before, after, "--exact"])
    assert rc == 2
    assert "direction_schedule" in capsys.readouterr().err


def test_regression_over_threshold_exits_nonzero(lc, tmp_path, monkeypatch,
                                                 capsys):
    before = _write_ledger(tmp_path / "b.json", {"net_apply": 1e-3})
    after = _write_ledger(tmp_path / "a.json", {"net_apply": 2e-3})
    rc = _run(lc, monkeypatch, [before, after])  # default 25% threshold
    assert rc == 2
    assert "REGRESSION" in capsys.readouterr().err
    # The same delta under a generous threshold passes.
    rc = _run(lc, monkeypatch, [before, after, "--threshold", "2.0"])
    assert rc == 0


def test_unparseable_capture_raises_systemexit(lc, tmp_path, monkeypatch):
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all\nstill not json\n")
    good = _write_ledger(tmp_path / "g.json", {"vperm": 1e-3})
    with pytest.raises(SystemExit):
        _run(lc, monkeypatch, [str(bad), good])


# ---------------------------------------------------------------------------
# Sharded (MULTICHIP) captures — ISSUE 11 satellite.
# ---------------------------------------------------------------------------

def _write_sharded(path, *, search_s=2e-3, bytes_total=448,
                   schedule=("bitmap", "delta"), per_shard_bytes=112):
    doc = {"details": {
        "sharded_phases": {
            "shards": 2,
            "phases": {
                "full_search": {"seconds": search_s,
                                "bytes_exchanged": bytes_total},
                "full_superstep": {"seconds": search_s / 4,
                                   "bytes_exchanged": bytes_total // 4},
            },
            "per_shard": [
                {"shard": s, "real_words": 10, "adj_entries": 500 + s,
                 "exchange_bytes_share": per_shard_bytes}
                for s in range(2)
            ],
        },
        "exchange": {"schedule": list(schedule),
                     "total_bytes": bytes_total},
        "direction_schedule": {"schedule": ["pull", "pull"]},
    }}
    path.write_text(json.dumps(doc))
    return str(path)


def test_sharded_capture_renders_bytes_and_shards(lc, tmp_path, monkeypatch,
                                                  capsys):
    before = _write_sharded(tmp_path / "b.json", bytes_total=1600,
                            schedule=["flat", "flat"], per_shard_bytes=800)
    after = _write_sharded(tmp_path / "a.json")
    rc = _run(lc, monkeypatch, [before, after])
    out = capsys.readouterr().out
    assert rc == 0  # bytes DROPPED — the compressed-exchange win
    assert "exchange bytes" in out
    assert "1600 -> 448" in out
    assert "| shard |" in out and "| 0 |" in out and "| 1 |" in out


def test_sharded_bytes_increase_is_a_regression(lc, tmp_path, monkeypatch,
                                                capsys):
    before = _write_sharded(tmp_path / "b.json", bytes_total=448)
    after = _write_sharded(tmp_path / "a.json", bytes_total=1600,
                           schedule=["flat", "flat"])
    rc = _run(lc, monkeypatch, [before, after])
    assert rc == 2
    assert "bytes" in capsys.readouterr().err


def test_sharded_exact_catches_arm_schedule_drift(lc, tmp_path, monkeypatch,
                                                  capsys):
    before = _write_sharded(tmp_path / "b.json")
    after = _write_sharded(tmp_path / "a.json",
                           schedule=("bitmap", "bitmap"))
    rc = _run(lc, monkeypatch, [before, after, "--exact"])
    assert rc == 2
    assert "exchange_schedule" in capsys.readouterr().err


def test_sharded_exact_passes_on_identical_captures(lc, tmp_path,
                                                    monkeypatch, capsys):
    before = _write_sharded(tmp_path / "b.json")
    after = _write_sharded(tmp_path / "a.json")
    rc = _run(lc, monkeypatch, [before, after, "--exact"])
    assert rc == 0
    assert "exact match" in capsys.readouterr().err
