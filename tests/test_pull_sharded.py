"""Sharded pull engine: the TPU-fast multi-chip formulation (vertex-
partitioned ELL + bit-packed frontier bitmap all-gather) vs the oracle.

This is the capability the reference's whole design is about — BFS
distributed across workers (BfsSpark.java:66-108, paper §1.5 varies 1/2/10
workers) — done as one `shard_map` program over the mesh's ``graph`` axis,
with distances AND parents asserted bit-exact against the canonical oracle
at shard counts 1/2/8 (the "N workers, one machine" methodology)."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import INF_DIST, build_device_graph
from bfs_tpu.graph.ell import build_sharded_pull_graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import bfs
from bfs_tpu.models.multisource import bfs_multi
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.parallel.sharded import bfs_sharded, bfs_sharded_multi, make_mesh


@pytest.mark.parametrize("num_shards", [1, 2, 8])
def test_pull_sharded_rmat_skewed(num_shards):
    """R-MAT degree skew exercises the fold recursion and hub vertices whose
    in-neighbours span many shards."""
    g = rmat_graph(9, 8, seed=11)
    mesh = make_mesh(graph=num_shards)
    res = bfs_sharded(g, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    d, _ = queue_bfs(g, 0)
    _, p = canonical_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert check(g, res.dist, res.parent, 0) == []


def test_pull_sharded_deep_graph():
    """A path graph maximizes superstep count (diameter = V-1): stresses the
    while_loop carry and repeated bitmap exchange."""
    g = path_graph(257)
    mesh = make_mesh(graph=8)
    res = bfs_sharded(g, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    d, p = queue_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert res.num_levels == 257  # 256 discovery levels + final empty check


def test_pull_sharded_disconnected_and_nonzero_source():
    g = gnm_graph(200, 220, seed=3)  # sparse: many unreachable vertices
    mesh = make_mesh(graph=4)
    res = bfs_sharded(g, 137, mesh=mesh, engine="pull", vertex_block_multiple=32)
    d, _ = queue_bfs(g, 137)
    _, p = canonical_bfs(g, 137)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert (res.dist == INF_DIST).any()  # genuinely exercises unreached


def test_pull_sharded_prebuilt_layout_reuse():
    g = rmat_graph(8, 6, seed=2)
    mesh = make_mesh(graph=2)
    spg = build_sharded_pull_graph(g, 2, block_multiple=32)
    assert spg.num_shards == 2
    for s in [0, 5, 100]:
        res = bfs_sharded(spg, s, mesh=mesh, engine="pull")
        d, _ = queue_bfs(g, s)
        np.testing.assert_array_equal(res.dist, d)


def test_pull_sharded_from_device_graph():
    """A pre-sharded push DeviceGraph is flattened and re-partitioned."""
    g = gnm_graph(100, 400, seed=7)
    dg = build_device_graph(g, num_shards=4, block=32)
    mesh = make_mesh(graph=2)
    res = bfs_sharded(dg, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    d, _ = queue_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)


def test_pull_sharded_shard_count_mismatch_rejected():
    g = gnm_graph(64, 128, seed=0)
    spg = build_sharded_pull_graph(g, 2, block_multiple=32)
    mesh = make_mesh(graph=4)
    with pytest.raises(ValueError):
        bfs_sharded(spg, 0, mesh=mesh, engine="pull")


def test_pull_sharded_matches_push_sharded_exactly():
    """The two multi-chip formulations are the same math: bit-exact on
    dist AND parent."""
    g = rmat_graph(8, 8, seed=21)
    mesh = make_mesh(graph=8)
    pull = bfs_sharded(g, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    push = bfs_sharded(g, 0, mesh=mesh, engine="push", block=16)
    np.testing.assert_array_equal(pull.dist, push.dist)
    np.testing.assert_array_equal(pull.parent, push.parent)
    assert pull.num_levels == push.num_levels


@pytest.mark.parametrize("batch,graph_shards", [(2, 4), (4, 2), (8, 1), (1, 8)])
def test_pull_sharded_multi_source_2d(batch, graph_shards):
    g = rmat_graph(8, 6, seed=13)
    mesh = make_mesh(graph=graph_shards, batch=batch)
    sources = [0, 3, 9, 27, 55, 81, 140, 200]
    res = bfs_sharded_multi(
        g, sources, mesh=mesh, engine="pull", vertex_block_multiple=32
    )
    ref = bfs_multi(g, sources)
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)


def test_pull_sharded_multi_source_repeated_sources():
    """The oracle's multi-source semantics allow duplicate sources
    (BreadthFirstPaths.java:114-132 enqueues each once); batched rows are
    independent, so duplicates must give identical rows."""
    g = gnm_graph(120, 360, seed=5)
    mesh = make_mesh(graph=4, batch=2)
    res = bfs_sharded_multi(
        g, [7, 7], mesh=mesh, engine="pull", vertex_block_multiple=32
    )
    np.testing.assert_array_equal(res.dist[0], res.dist[1])
    np.testing.assert_array_equal(res.parent[0], res.parent[1])
    d, _ = queue_bfs(g, 7)
    np.testing.assert_array_equal(res.dist[0], d)


def test_pull_sharded_single_chip_equivalence():
    """Sharded at n=1 must agree with the single-chip pull engine (the
    no-regression anchor: same layout family, same math)."""
    g = rmat_graph(9, 6, seed=4)
    mesh = make_mesh(graph=1)
    sharded = bfs_sharded(g, 0, mesh=mesh, engine="pull", vertex_block_multiple=32)
    single = bfs(g, 0, engine="pull")
    np.testing.assert_array_equal(sharded.dist, single.dist)
    np.testing.assert_array_equal(sharded.parent, single.parent)
