"""Relay engine (degree classes + Beneš bit routing) vs oracle and engines."""

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph.csr import Graph, INF_DIST
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import RelayEngine, bfs
from bfs_tpu.oracle.bfs import canonical_bfs, check

pytestmark = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


# ---- Beneš building blocks --------------------------------------------------

def test_route_random_perms():
    rng = np.random.default_rng(1)
    for _ in range(30):
        n = 1 << int(rng.integers(5, 12))
        perm = rng.permutation(n).astype(np.int64)
        masks = benes.route(perm)
        x = rng.integers(0, 2, size=n).astype(np.uint8)
        np.testing.assert_array_equal(benes.apply_network_numpy(masks, x), x[perm])


def test_route_rejects_non_bijection():
    with pytest.raises(ValueError):
        benes.route(np.zeros(8, dtype=np.int64))
    with pytest.raises(ValueError):
        benes.route(np.arange(6, dtype=np.int64))  # not a power of two


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(2)
    bits = rng.integers(0, 2, size=256).astype(np.uint8)
    np.testing.assert_array_equal(benes.unpack_bits(benes.pack_bits(bits)), bits)


def test_ops_pack_bits_layout_and_batching():
    """ops.relay.pack_bits agrees with the numpy reference layout (bit-major:
    element e -> word e % nw, bit e // nw), for bool and uint8 inputs and
    with leading batch axes (the sharded/batched engines' path)."""
    import jax.numpy as jnp

    from bfs_tpu.ops.relay import pack_bits, unpack_bits

    rng = np.random.default_rng(9)
    for n in (64, 4096):
        nw = n // 32
        bits = rng.integers(0, 2, size=n).astype(np.uint8)
        want = np.zeros(nw, dtype=np.uint32)
        for e in range(n):
            if bits[e]:
                want[e % nw] |= np.uint32(1) << (e // nw)
        got = np.asarray(pack_bits(jnp.asarray(bits), n))
        np.testing.assert_array_equal(got, want)
        got_bool = np.asarray(pack_bits(jnp.asarray(bits.astype(bool)), n))
        np.testing.assert_array_equal(got_bool, want)
        np.testing.assert_array_equal(
            np.asarray(unpack_bits(jnp.asarray(want), n)), bits
        )
    batched = rng.integers(0, 2, size=(3, 2048)).astype(np.uint8)
    got = np.asarray(pack_bits(jnp.asarray(batched), 2048))
    for i in range(3):
        np.testing.assert_array_equal(
            got[i], np.asarray(pack_bits(jnp.asarray(batched[i]), 2048))
        )


def test_xla_applier_matches_numpy():
    import jax.numpy as jnp

    from bfs_tpu.ops.relay import MIN_PACKED_BITS, apply_benes, pack_bits, unpack_bits

    rng = np.random.default_rng(3)
    # Covers the unpacked small path, the packed path's word/lane stages,
    # and (at 2^21) row-block stages.
    for n in (32, 64, 2048, MIN_PACKED_BITS, 1 << 17, 1 << 21):
        perm = rng.permutation(n).astype(np.int64)
        masks = benes.route(perm, bit_major=True)
        bits = rng.integers(0, 2, size=n).astype(np.uint8)
        want = bits[perm]
        got = np.asarray(
            unpack_bits(
                apply_benes(pack_bits(jnp.asarray(bits), n), jnp.asarray(masks), n),
                n,
            )
        )
        np.testing.assert_array_equal(got, want)


def test_route_bit_major_matches_numpy_applier():
    rng = np.random.default_rng(4)
    for n in (64, 1024):
        perm = rng.permutation(n).astype(np.int64)
        masks = benes.route(perm, bit_major=True)
        x = rng.integers(0, 100, size=n)
        np.testing.assert_array_equal(
            benes.apply_network_numpy(masks, x, bit_major=True), x[perm]
        )


# ---- end-to-end relay BFS ---------------------------------------------------

def _assert_relay_matches(graph, source=0):
    result = bfs(graph, source, engine="relay")
    dist, parent = canonical_bfs(graph, source)
    np.testing.assert_array_equal(result.dist, dist)
    np.testing.assert_array_equal(result.parent, parent)
    assert check(graph, result.dist, result.parent, source) == []


def test_tiny_relay(tiny_graph):
    result = bfs(tiny_graph, 0, engine="relay")
    assert result.dist.tolist() == [0, 1, 1, 2, 2, 1]
    assert result.parent.tolist() == [0, 0, 0, 2, 2, 0]
    assert result.num_levels == 3


def test_relay_random_graphs():
    for seed in range(4):
        g = gnm_graph(150, 500, seed=seed)
        _assert_relay_matches(g, seed % 150)


def test_relay_rmat_skewed():
    g = rmat_graph(9, 8, seed=7)
    _assert_relay_matches(g, 0)


def test_relay_path_and_disconnected():
    _assert_relay_matches(path_graph(70), 0)
    g = Graph.from_undirected_edges(6, np.array([[0, 1], [3, 4]]))
    r = bfs(g, 0, engine="relay")
    assert r.dist[1] == 1 and r.dist[3] == INF_DIST and r.parent[4] == -1


def test_relay_engine_reuse_multiple_sources():
    g = gnm_graph(120, 400, seed=11)
    eng = RelayEngine(g)
    for s in (0, 5, 77):
        r = eng.run(s)
        dist, parent = canonical_bfs(g, s)
        np.testing.assert_array_equal(r.dist, dist)
        np.testing.assert_array_equal(r.parent, parent)


def test_relay_matches_pull_engine():
    g = gnm_graph(200, 700, seed=3)
    a = bfs(g, 4, engine="relay")
    b = bfs(g, 4, engine="pull")
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)
