"""Relay engine (degree classes + Beneš bit routing) vs oracle and engines."""

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph.csr import Graph, INF_DIST
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import RelayEngine, bfs
from bfs_tpu.oracle.bfs import canonical_bfs, check

pytestmark = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


# ---- Beneš building blocks --------------------------------------------------

def test_route_random_perms():
    rng = np.random.default_rng(1)
    for _ in range(30):
        n = 1 << int(rng.integers(5, 12))
        perm = rng.permutation(n).astype(np.int64)
        masks = benes.route(perm)
        x = rng.integers(0, 2, size=n).astype(np.uint8)
        np.testing.assert_array_equal(benes.apply_network_numpy(masks, x), x[perm])


def test_route_rejects_non_bijection():
    with pytest.raises(ValueError):
        benes.route(np.zeros(8, dtype=np.int64))
    with pytest.raises(ValueError):
        benes.route(np.arange(6, dtype=np.int64))  # not a power of two


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(2)
    bits = rng.integers(0, 2, size=256).astype(np.uint8)
    np.testing.assert_array_equal(benes.unpack_bits(benes.pack_bits(bits)), bits)


def test_ops_pack_std_layout_and_batching():
    """ops.relay.pack_std agrees with the standard packing (element e ->
    word e >> 5, bit e & 31), for bool and uint8 inputs and with leading
    batch axes (the sharded/batched engines' path)."""
    import jax.numpy as jnp

    from bfs_tpu.ops.relay import pack_std, unpack_std

    rng = np.random.default_rng(9)
    for n in (64, 4096):
        nw = n // 32
        bits = rng.integers(0, 2, size=n).astype(np.uint8)
        want = np.zeros(nw, dtype=np.uint32)
        for e in range(n):
            if bits[e]:
                want[e >> 5] |= np.uint32(1) << (e & 31)
        got = np.asarray(pack_std(jnp.asarray(bits)))
        np.testing.assert_array_equal(got, want)
        got_bool = np.asarray(pack_std(jnp.asarray(bits.astype(bool))))
        np.testing.assert_array_equal(got_bool, want)
        np.testing.assert_array_equal(
            np.asarray(unpack_std(jnp.asarray(want), n)), bits
        )
    batched = rng.integers(0, 2, size=(3, 2048)).astype(np.uint8)
    got = np.asarray(pack_std(jnp.asarray(batched)))
    for i in range(3):
        np.testing.assert_array_equal(
            got[i], np.asarray(pack_std(jnp.asarray(batched[i])))
        )


def test_xla_applier_matches_numpy():
    """apply_benes_std (v4 stage table: full + pair-compacted masks with
    nonzero ranges) routes exactly perm for all stage regimes."""
    import jax.numpy as jnp

    from bfs_tpu.graph.relay import _compact_and_table
    from bfs_tpu.ops.relay import apply_benes_std, pack_std, unpack_std

    rng = np.random.default_rng(3)
    for n in (64, 2048, 1 << 13, 1 << 17, 1 << 21):
        perm = rng.permutation(n).astype(np.int64)
        masks_full = benes.route_std(perm)
        masks, table = _compact_and_table(masks_full, n)
        bits = rng.integers(0, 2, size=n).astype(np.uint8)
        want = bits[perm]
        got = np.asarray(
            unpack_std(
                apply_benes_std(
                    pack_std(jnp.asarray(bits)), jnp.asarray(masks), table, n
                ),
                n,
            )
        )
        np.testing.assert_array_equal(got, want)


def test_route_bit_major_matches_numpy_applier():
    rng = np.random.default_rng(4)
    for n in (64, 1024):
        perm = rng.permutation(n).astype(np.int64)
        masks = benes.route(perm, bit_major=True)
        x = rng.integers(0, 100, size=n)
        np.testing.assert_array_equal(
            benes.apply_network_numpy(masks, x, bit_major=True), x[perm]
        )


# ---- end-to-end relay BFS ---------------------------------------------------

def _assert_relay_matches(graph, source=0):
    result = bfs(graph, source, engine="relay")
    dist, parent = canonical_bfs(graph, source)
    np.testing.assert_array_equal(result.dist, dist)
    np.testing.assert_array_equal(result.parent, parent)
    assert check(graph, result.dist, result.parent, source) == []


def test_tiny_relay(tiny_graph):
    result = bfs(tiny_graph, 0, engine="relay")
    assert result.dist.tolist() == [0, 1, 1, 2, 2, 1]
    assert result.parent.tolist() == [0, 0, 0, 2, 2, 0]
    assert result.num_levels == 3


def test_relay_random_graphs():
    for seed in range(4):
        g = gnm_graph(150, 500, seed=seed)
        _assert_relay_matches(g, seed % 150)


def test_relay_rmat_skewed():
    g = rmat_graph(9, 8, seed=7)
    _assert_relay_matches(g, 0)


def test_relay_path_and_disconnected():
    _assert_relay_matches(path_graph(70), 0)
    g = Graph.from_undirected_edges(6, np.array([[0, 1], [3, 4]]))
    r = bfs(g, 0, engine="relay")
    assert r.dist[1] == 1 and r.dist[3] == INF_DIST and r.parent[4] == -1


def test_relay_engine_reuse_multiple_sources():
    g = gnm_graph(120, 400, seed=11)
    eng = RelayEngine(g)
    for s in (0, 5, 77):
        r = eng.run(s)
        dist, parent = canonical_bfs(g, s)
        np.testing.assert_array_equal(r.dist, dist)
        np.testing.assert_array_equal(r.parent, parent)


def test_relay_matches_pull_engine():
    g = gnm_graph(200, 700, seed=3)
    a = bfs(g, 4, engine="relay")
    b = bfs(g, 4, engine="pull")
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)
