"""Connected components on the semiring substrate (ISSUE 16).

Covers: label-min propagation vs the union-find oracle (min-id canonical
labels, bit-for-bit) on multi-component gnm / star / path / rmat; the
push and pull arms' value identity plus the density-based ``auto``
resolution; fused-vs-segmented bit-identity incl. the in-process
kill/resume chaos smoke; x2/x8 edge-sharded parity; the on-device label
invariant counters; and the result's component-query surface.
"""

import os

import numpy as np
import pytest

from bfs_tpu.algo import cc, cc_segmented, cc_sharded
from bfs_tpu.graph.generators import (
    gnm_graph,
    path_graph,
    rmat_graph,
    star_graph,
)
from bfs_tpu.oracle import cc_device_check, check_cc, union_find_labels
from bfs_tpu.resilience import faults
from bfs_tpu.resilience.faults import FaultInjected
from bfs_tpu.resilience.superstep_ckpt import CkptConfig, SuperstepCheckpointer

GRAPHS = {
    # Sparse G(n, m): isolated vertices + several components — the
    # rootless semiring's reason to exist (BFS needs a root per island).
    "gnm_multi": lambda: gnm_graph(200, 150, seed=7),
    "star": lambda: star_graph(64),
    "path": lambda: path_graph(200),
    "rmat": lambda: rmat_graph(7, 8, seed=2),
}

_cache: dict[str, object] = {}


@pytest.fixture(params=sorted(GRAPHS))
def graph(request):
    if request.param not in _cache:
        _cache[request.param] = GRAPHS[request.param]()
    return _cache[request.param]


def _mgr(tmp_path, k=1):
    return SuperstepCheckpointer(
        tmp_path, {"algo": "cc"}, cfg=CkptConfig("every", k)
    )


# -------------------------------------------------------- oracle parity --
@pytest.mark.algo_smoke
@pytest.mark.parametrize("engine", ["push", "pull"])
def test_cc_matches_union_find(graph, engine):
    oracle = union_find_labels(graph)
    res = cc(graph, engine=engine)
    assert res.engine == engine
    np.testing.assert_array_equal(res.label, oracle)
    assert check_cc(graph, res.label) == []
    assert res.num_components == int(np.unique(oracle).size)


def test_cc_auto_engine_resolution():
    dense = gnm_graph(64, 1024, seed=1)  # E/V >= 8 -> pull
    sparse = path_graph(64)
    assert cc(dense, engine="auto").engine == "pull"
    assert cc(sparse, engine="auto").engine == "push"
    np.testing.assert_array_equal(
        cc(dense, engine="auto").label, union_find_labels(dense)
    )


def test_cc_component_queries():
    g = GRAPHS["gnm_multi"]()
    res = cc(g)
    oracle = union_find_labels(g)
    assert res.num_components > 1
    same = np.flatnonzero(oracle == oracle[g.src[0]])
    assert res.same_component(int(same[0]), int(same[-1]))
    other = np.flatnonzero(oracle != oracle[g.src[0]])
    assert not res.same_component(int(same[0]), int(other[0]))


# ---------------------------------------------------------- device check --
def test_cc_device_check(graph):
    res = cc(graph)
    assert cc_device_check(
        graph.src, graph.dst, res.label, graph.num_vertices
    ) == {}
    bad = res.label.copy()
    v = graph.num_vertices - 1
    bad[v] = v  # detach the last vertex from its component's label
    viol = cc_device_check(graph.src, graph.dst, bad, graph.num_vertices)
    if int(res.label[v]) != v:  # was not already its own representative
        assert viol


# ------------------------------------------------- segmented / kill-resume --
@pytest.mark.algo_smoke
def test_cc_segmented_bit_identical(graph, tmp_path):
    fused = cc(graph)
    for k in (2, 3):
        res = cc_segmented(graph, ckpt=_mgr(tmp_path / f"k{k}", k=k))
        np.testing.assert_array_equal(res.label, fused.label)
        assert res.rounds == fused.rounds


@pytest.mark.chaos
def test_cc_kill_resume_bit_identical(tmp_path):
    g = GRAPHS["gnm_multi"]()
    fused = cc(g)
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            cc_segmented(g, ckpt=_mgr(tmp_path))
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = _mgr(tmp_path)
    res = cc_segmented(g, ckpt=mgr)
    assert mgr.report()["resumed_from_epoch"] == 2
    np.testing.assert_array_equal(res.label, fused.label)
    assert res.rounds == fused.rounds


# ----------------------------------------------------------------- sharded --
@pytest.mark.algo_smoke
@pytest.mark.parametrize("shards", [2, 8])
def test_cc_sharded_parity(graph, shards):
    base = cc(graph)
    res = cc_sharded(graph, num_shards=shards)
    assert res.engine == f"push_sharded_x{shards}"
    np.testing.assert_array_equal(res.label, base.label)
    assert res.rounds == base.rounds
