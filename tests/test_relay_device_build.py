"""Device-side relay layout builder (graph/relay_device.py) vs the host
oracle builder: bit-parity on rmat/gnm/star/path fixtures across both
segment arms, semantic equivalence of the pure-JAX route arm, end-to-end
oracle-exact BFS through device-built layouts on every relay path, and the
``BFS_TPU_LAYOUT_BUILD`` flavor knob in the bundle store."""

import numpy as np
import pytest

from bfs_tpu.graph import benes
from bfs_tpu.graph import relay
from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.graph.relay_device import (
    build_relay_graph_device,
    resolve_route,
    resolve_segments,
    route_masks_device,
)
from bfs_tpu.models.bfs import RelayEngine
from bfs_tpu.oracle.bfs import canonical_bfs, check

requires_native = pytest.mark.skipif(
    not benes.native_available(), reason="native benes router unavailable"
)


def star_graph(n: int = 96) -> Graph:
    """Hub 0 <-> every other vertex: one huge-width out class next to a
    width-1 class — the vertex-major/rank-major mix in one fixture."""
    edges = np.stack([np.zeros(n - 1, dtype=np.int64),
                      np.arange(1, n, dtype=np.int64)], axis=1)
    return Graph.from_undirected_edges(n, edges)


def _fixtures():
    return [
        ("rmat", rmat_graph(9, 8, seed=7)),
        ("gnm", gnm_graph(300, 1800, seed=3)),
        ("star", star_graph()),
        ("path", path_graph(70)),
    ]


_ARRAY_FIELDS = (
    "new2old", "old2new", "src_l1", "adj_indptr", "adj_dst", "adj_slot",
)
_SCALAR_FIELDS = (
    "num_vertices", "num_edges", "vr", "vperm_size", "out_space",
    "net_size", "m1", "m2",
)


def _assert_same_construction(host, dev, tag):
    """Classes/slots/permutation-level equality: every field EXCEPT the
    routing masks is bit-identical (the 'identical classes/slots/perm'
    half of the parity contract)."""
    for f in _SCALAR_FIELDS:
        assert getattr(host, f) == getattr(dev, f), (tag, f)
    for f in _ARRAY_FIELDS:
        np.testing.assert_array_equal(
            getattr(host, f), getattr(dev, f), err_msg=f"{tag}:{f}"
        )
    assert repr(host.in_classes) == repr(dev.in_classes), tag
    assert repr(host.out_classes) == repr(dev.out_classes), tag


def _assert_same_masks(host, dev, tag):
    np.testing.assert_array_equal(
        host.net_masks, dev.net_masks, err_msg=f"{tag}:net_masks"
    )
    np.testing.assert_array_equal(
        host.vperm_masks, dev.vperm_masks, err_msg=f"{tag}:vperm_masks"
    )
    assert repr(host.net_table) == repr(dev.net_table), tag
    assert repr(host.vperm_table) == repr(dev.vperm_table), tag


# ---- builder parity ---------------------------------------------------------

@requires_native
@pytest.mark.parametrize("segments", ["host", "xla"])
def test_device_builder_bit_identical_native_route(segments):
    """With the native route arm the device builder is BIT-IDENTICAL to the
    host builder — masks, stage tables, classes, slots, CSR, everything —
    on all four fixture shapes, under both segment arms."""
    for tag, g in _fixtures():
        host = relay.build_relay_graph(g)
        dev = build_relay_graph_device(g, route="native", segments=segments)
        _assert_same_construction(host, dev, f"{tag}/{segments}")
        _assert_same_masks(host, dev, f"{tag}/{segments}")


@requires_native
@pytest.mark.parametrize("segments", ["host", "xla"])
def test_jax_route_semantic_equivalence(segments):
    """The pure-JAX route arm: identical classes/slots/perm (every
    non-mask field bit-identical), masks allowed to differ — documented
    semantic equivalence."""
    for tag, g in [("rmat", rmat_graph(8, 8, seed=5)), ("path", path_graph(40))]:
        host = relay.build_relay_graph(g)
        dev = build_relay_graph_device(g, route="jax", segments=segments)
        _assert_same_construction(host, dev, f"{tag}/{segments}")


def test_jax_router_routes_arbitrary_permutations():
    """route_masks_device's masks realize exactly ``y[j] = x[perm[j]]`` on
    the standard stage topology (the same applier contract as the native
    router), including the all-identity permutation, which must route
    switch-free (zero masks -> shrunken stage ranges)."""
    rng = np.random.default_rng(11)
    for n in (32, 256, 4096):
        perm = rng.permutation(n).astype(np.int32)
        masks = np.asarray(route_masks_device(perm, n=n))
        x = rng.integers(0, 1 << 30, size=n)
        np.testing.assert_array_equal(
            benes.apply_network_numpy(masks, x), x[perm]
        )
    ident = np.arange(1024, dtype=np.int32)
    assert not np.asarray(route_masks_device(ident, n=1024)).any()


def test_stage_times_and_arm_resolution():
    g = gnm_graph(120, 500, seed=1)
    times = {}
    build_relay_graph_device(
        g, route=resolve_route(None), stage_times=times
    )
    assert times["segments"] == resolve_segments(None)
    assert times["route"] in ("native", "jax")
    assert times["compile_seconds"] >= 0.0
    stage_keys = [
        k for k, v in times.items() if isinstance(v, float) and k not in (
            "compile_seconds",
        )
    ]
    # per-stage timings: the classing prelude, both routes, a compaction
    assert any(k.startswith("route_net") for k in stage_keys)
    assert any(k.startswith("route_vperm") for k in stage_keys)
    assert any("compact" in k for k in stage_keys)
    with pytest.raises(ValueError):
        resolve_segments("gpu")
    with pytest.raises(ValueError):
        resolve_route("fastest")


# ---- end-to-end BFS through device-built layouts ----------------------------

@requires_native
def test_bfs_oracle_exact_packed_and_sparse_paths():
    """Oracle-exact BFS with canonical parents through a device-built
    layout on the packed dense path and the sparse hybrid path."""
    g = gnm_graph(200, 900, seed=5)
    rg = build_relay_graph_device(g)
    for sparse in (False, True):
        eng = RelayEngine(rg, sparse_hybrid=sparse)
        for s in (0, 17, 140):
            r = eng.run(s)
            dist, parent = canonical_bfs(g, s)
            np.testing.assert_array_equal(r.dist, dist)
            np.testing.assert_array_equal(r.parent, parent)
            assert check(g, r.dist, r.parent, s) == []


@requires_native
def test_bfs_oracle_exact_multisource_path():
    """Batched multi-source BFS through a device-built layout matches the
    canonical per-source trees."""
    g = gnm_graph(150, 600, seed=9)
    rg = build_relay_graph_device(g)
    eng = RelayEngine(rg)
    sources = [0, 31, 77, 149]
    res = eng.run_multi(sources)
    for i, s in enumerate(sources):
        dist, parent = canonical_bfs(g, s)
        np.testing.assert_array_equal(res.dist[i], dist)
        np.testing.assert_array_equal(res.parent[i], parent)
        assert check(g, res.dist[i], res.parent[i], s) == []


@requires_native
def test_bfs_oracle_exact_jax_routed_layout():
    """The no-native route arm end-to-end: a jax-routed device layout
    still solves oracle-exactly (its masks differ from the native
    router's but route the same permutation)."""
    g = rmat_graph(8, 6, seed=2)
    rg = build_relay_graph_device(g, route="jax")
    eng = RelayEngine(rg, sparse_hybrid=True)
    r = eng.run(3)
    dist, parent = canonical_bfs(g, 3)
    np.testing.assert_array_equal(r.dist, dist)
    np.testing.assert_array_equal(r.parent, parent)
    assert check(g, r.dist, r.parent, 3) == []


# ---- the flavor knob in the bundle store ------------------------------------

@requires_native
def test_load_or_build_relay_builder_flavors(tmp_path, monkeypatch):
    """Default first-touch path is the device builder; BFS_TPU_LAYOUT_BUILD
    =host selects the oracle; bundle bytes are identical either way, and a
    warm hit replays the cold build's provenance."""
    from bfs_tpu.cache.layout import LayoutCache, load_or_build_relay
    from bfs_tpu.graph.relay import relay_to_arrays

    monkeypatch.delenv("BFS_TPU_LAYOUT_BUILD", raising=False)
    g = gnm_graph(100, 300, seed=4)
    cache = LayoutCache(str(tmp_path / "dev"))
    rg, info = load_or_build_relay(g, cache=cache)
    assert info["cache"] == "miss" and info["builder"] == "device"
    assert info["build_stages"]["segments"] in ("host", "xla")
    assert info["build_stages"]["route"] in ("native", "jax")
    _, info_hit = load_or_build_relay(g, cache=cache)
    assert info_hit["cache"] == "hit"
    assert info_hit["builder"] == "device"  # provenance from bundle meta
    assert "build_stages" in info_hit

    monkeypatch.setenv("BFS_TPU_LAYOUT_BUILD", "host")
    rg_host, info_host = load_or_build_relay(
        g, cache=LayoutCache(str(tmp_path / "host"))
    )
    assert info_host["builder"] == "host"
    a, b = relay_to_arrays(rg), relay_to_arrays(rg_host)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    monkeypatch.setenv("BFS_TPU_LAYOUT_BUILD", "banana")
    with pytest.raises(ValueError):
        load_or_build_relay(g, cache=None)


def test_device_build_failure_falls_back_to_host(monkeypatch):
    """A device-builder failure must degrade to the host oracle builder
    (with the failure recorded), never fail the registration/build."""
    import bfs_tpu.graph.relay_device as rd
    from bfs_tpu.cache.layout import load_or_build_relay

    def boom(*a, **kw):
        raise RuntimeError("injected device-build failure")

    monkeypatch.setattr(rd, "build_relay_graph_device", boom)
    monkeypatch.delenv("BFS_TPU_LAYOUT_BUILD", raising=False)
    g = gnm_graph(80, 240, seed=6)
    rg, info = load_or_build_relay(g, cache=None)
    assert info["builder"] == "host"
    assert "injected device-build failure" in info["build_stages"]["fallback"]
    host = relay.build_relay_graph(g)
    np.testing.assert_array_equal(rg.src_l1, host.src_l1)


def test_width_table_matches_class_width():
    """The searchsorted candidate-table classing (device + sharded shared
    helper) is exactly `_class_width` over the full degree range."""
    cand = relay.width_candidates()
    deg = np.concatenate([
        np.arange(0, 4096),
        (1 << np.arange(0, 30)).astype(np.int64),
        (3 << np.arange(0, 28)).astype(np.int64),
        (1 << np.arange(2, 30)) - 1,
        (1 << np.arange(2, 30)) + 1,
    ])
    np.testing.assert_array_equal(
        relay._class_width(deg), cand[relay.width_index(deg, cand)]
    )
