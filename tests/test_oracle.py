"""Sequential oracle tests: distances/parents/paths on the paper's worked
example (docs/BigData_Project.pdf §1.2 Table 2), check() invariants
(BreadthFirstPaths.java:172-221 semantics), multi-source, native parity."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import Graph, INF_DIST, NO_PARENT
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.graph.vertex import path_to
from bfs_tpu.oracle.bfs import canonical_bfs, check, dist_to, has_path_to, queue_bfs
from bfs_tpu.oracle.native import native_available, native_bfs, native_check

TINY_DIST = [0, 1, 1, 2, 2, 1]
TINY_PARENT = [0, 0, 0, 2, 2, 0]  # canonical min-parent


def test_queue_bfs_tiny(tiny_graph):
    dist, parent = queue_bfs(tiny_graph, 0)
    assert dist.tolist() == TINY_DIST
    assert parent.tolist() == TINY_PARENT  # sorted adjacency makes these agree
    assert check(tiny_graph, dist, parent, 0) == []


def test_canonical_bfs_tiny(tiny_graph):
    dist, parent = canonical_bfs(tiny_graph, 0)
    assert dist.tolist() == TINY_DIST
    assert parent.tolist() == TINY_PARENT
    # Paper Table 2: path to 3 is "0,5,3 or 0,2,3 depending on the order";
    # the canonical min-parent rule makes it deterministically 0-2-3.
    assert path_to(parent, 3) == [0, 2, 3]
    assert path_to(parent, 4) == [0, 2, 4]


def test_query_api(tiny_graph):
    dist, parent = queue_bfs(tiny_graph, 0)
    assert has_path_to(dist, 3)
    assert dist_to(dist, 3) == 2
    assert path_to(parent, 0) == [0]


def test_disconnected():
    g = Graph.from_undirected_edges(5, np.array([[0, 1], [2, 3]]))
    dist, parent = queue_bfs(g, 0)
    assert dist[2] == INF_DIST and dist[4] == INF_DIST
    assert parent[2] == NO_PARENT
    assert not has_path_to(dist, 4)
    assert path_to(parent, 4) == []
    # check() must flag nothing: unreached vertices are legal (Color.java:13-16).
    assert check(g, dist, parent, 0) == []


def test_multi_source():
    g = path_graph(10)
    dist, parent = queue_bfs(g, [0, 9])
    # BreadthFirstPaths multi-source semantics: dist to the NEAREST source.
    assert dist.tolist() == [0, 1, 2, 3, 4, 4, 3, 2, 1, 0]
    assert check(g, dist, parent, [0, 9]) == []


def test_canonical_vs_queue_distances_agree():
    for seed in range(5):
        g = gnm_graph(200, 500, seed=seed)
        d1, p1 = queue_bfs(g, 0)
        d2, p2 = canonical_bfs(g, 0)
        np.testing.assert_array_equal(d1, d2)
        assert check(g, d2, p2, 0) == []


def test_check_catches_corruption(tiny_graph):
    dist, parent = queue_bfs(tiny_graph, 0)
    bad = dist.copy()
    bad[3] = 7  # violates triangle inequality
    assert check(tiny_graph, bad, parent, 0) != []
    bad2 = dist.copy()
    bad2[0] = 1  # source distance must be 0
    assert check(tiny_graph, bad2, parent, 0) != []
    badp = parent.copy()
    badp[3] = 1  # 1-3 is not an edge / wrong level
    assert check(tiny_graph, dist, badp, 0) != []


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
class TestNativeOracle:
    def test_native_matches_python_queue(self, tiny_graph):
        dist, parent, levels = native_bfs(tiny_graph, 0, policy="queue")
        d, p = queue_bfs(tiny_graph, 0)
        np.testing.assert_array_equal(dist, d)
        np.testing.assert_array_equal(parent, p)
        assert levels == 2

    def test_native_canonical_matches(self):
        for seed in range(3):
            g = rmat_graph(7, 4, seed=seed)
            dist, parent, _ = native_bfs(g, 0, policy="canonical")
            d, p = canonical_bfs(g, 0)
            np.testing.assert_array_equal(dist, d)
            np.testing.assert_array_equal(parent, p)

    def test_native_check(self, tiny_graph):
        dist, parent, _ = native_bfs(tiny_graph, 0)
        assert native_check(tiny_graph, dist, parent, 0) == 0
        bad = dist.copy()
        bad[0] = 5
        assert native_check(tiny_graph, bad, parent, 0) != 0

    def test_native_multi_source(self):
        g = path_graph(10)
        dist, _, levels = native_bfs(g, [0, 9])
        assert dist.tolist() == [0, 1, 2, 3, 4, 4, 3, 2, 1, 0]
        assert levels == 4


def test_check_directed_graph_no_false_positive():
    # A correct BFS over a directed graph must not trip the reachability
    # check: unreachable->reachable directed edges are legal.
    g = Graph.from_directed_edges(3, np.array([[0, 1], [2, 1]]))
    dist, parent = queue_bfs(g, 0)
    assert dist.tolist() == [0, 1, INF_DIST]
    assert check(g, dist, parent, 0) == []
