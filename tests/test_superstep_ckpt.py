"""Superstep-granular checkpoint/restore (ISSUE 14).

Covers: BFS_TPU_CKPT resolution and the Young/Daly interval, the ops-level
reference segment runners, fused-vs-segmented bit-identity for the relay /
multisource / x8 sharded programs (dist, parent, direction schedule,
exchange-arm sequence), mid-traversal kill/resume (the ``chaos``-marked
smoke ci_gate runs), the checkpoint corruption matrix (newest epoch
damaged -> previous; all damaged -> clean fresh run), per-shard epoch
shard loss, the ``superstep:<n>`` fault family, and the serve hung-call
resume path.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import REPO_ROOT

from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.resilience import faults
from bfs_tpu.resilience.faults import FaultInjected, corrupt_file, fault_spec
from bfs_tpu.resilience.superstep_ckpt import (
    CkptConfig,
    SuperstepCheckpointer,
    daly_interval,
    resolve_ckpt,
    run_multi_segmented,
)

SOURCE = 3


@pytest.fixture(scope="module")
def graph():
    return rmat_graph(8, 4, seed=3)


@pytest.fixture(scope="module")
def eng(graph):
    from bfs_tpu.models.bfs import RelayEngine

    # Auto direction + sparse hybrid: the carry holds the hysteresis
    # pair, so resume must restore it — the hardest single-chip flavor.
    return RelayEngine(graph, sparse_hybrid=True, direction="auto")


@pytest.fixture(scope="module")
def golden(eng):
    result = eng.run(SOURCE)
    curve = eng.run_level_curve(SOURCE)
    return result, curve


def _mgr(tmp_path, k=2, config=None, **kw):
    return SuperstepCheckpointer(
        tmp_path, config if config is not None else {"t": 1},
        cfg=CkptConfig("every", k), **kw,
    )


def _assert_identical(res, curve, golden):
    gres, gcurve = golden
    np.testing.assert_array_equal(res.dist, gres.dist)
    np.testing.assert_array_equal(res.parent, gres.parent)
    assert res.num_levels == gres.num_levels
    if curve is not None:
        # The ISSUE 14 assertion: the resumed run reproduces the killed
        # run's direction schedule exactly — it is a pure function of
        # graph + thresholds and the hysteresis state rides the carry.
        assert (
            curve["direction_schedule"]["schedule"]
            == gcurve["direction_schedule"]["schedule"]
        )
        assert curve["occupancy"] == gcurve["occupancy"]


# ------------------------------------------------------------ knob parsing --
def test_resolve_ckpt_default_off(monkeypatch):
    monkeypatch.delenv("BFS_TPU_CKPT", raising=False)
    cfg = resolve_ckpt()
    assert cfg.mode == "off" and not cfg.enabled


def test_resolve_ckpt_spellings():
    assert resolve_ckpt("every:5") == CkptConfig("every", 5)
    assert resolve_ckpt("every").k >= 1
    assert resolve_ckpt("auto").mode == "auto"
    assert resolve_ckpt("off").enabled is False
    with pytest.raises(ValueError):
        resolve_ckpt("always")
    with pytest.raises(ValueError):
        resolve_ckpt("every:0")
    with pytest.raises(ValueError):
        resolve_ckpt("auto:3")


def test_daly_interval_shape():
    # Cheaper snapshots (or a flakier environment) checkpoint more often.
    assert daly_interval(0.1, 1e-4, 600) < daly_interval(0.1, 1.0, 600)
    assert daly_interval(0.1, 0.01, 60) < daly_interval(0.1, 0.01, 6000)
    # Slower supersteps need fewer of them per segment.
    assert daly_interval(10.0, 0.01, 600) <= daly_interval(0.01, 0.01, 600)
    # Clamps.
    assert daly_interval(1e9, 1e-6, 1) == 1
    assert daly_interval(1e-9, 10, 1e9) == 4096


def test_auto_interval_rederived_from_measurements(tmp_path):
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": 1}, cfg=CkptConfig("auto"), mtbf_s=600
    )
    k0 = mgr.interval()
    mgr.save_epoch(1, {"x": np.zeros(4, np.int32)})
    mgr.note_segment(1, 0.5)
    assert mgr.interval() == daly_interval(
        mgr._superstep_s, mgr._snapshot_s, 600
    )
    assert mgr.report()["mode"] == "auto"
    assert isinstance(k0, int)


# ------------------------------------------------- ops reference segments --
def test_ops_segment_runner_parity(eng):
    """Segments of any size composed back-to-back equal one full loop
    (ops/relay.relay_segment_words — the XLA reference segment runner).
    ``seg_end`` is a traced operand, so ONE compiled program serves the
    full run and every partial segment."""
    import functools

    import jax
    import jax.numpy as jnp

    from bfs_tpu.graph.relay import valid_slot_words
    from bfs_tpu.ops import relay as R

    rg = eng.relay_graph
    layout = dict(
        vperm_masks=jnp.asarray(rg.vperm_masks),
        vperm_table=rg.vperm_table, vperm_size=rg.vperm_size,
        out_classes=tuple(rg.out_classes), out_space=rg.out_space,
        net_masks=jnp.asarray(rg.net_masks), net_table=rg.net_table,
        net_size=rg.net_size, in_classes=tuple(rg.in_classes),
        valid_words=jnp.asarray(valid_slot_words(rg.src_l1, rg.net_size)),
        vr=rg.vr,
    )
    seg = jax.jit(
        functools.partial(R.relay_segment_words, cap=rg.vr, **layout)
    )
    sn = int(rg.old2new[SOURCE])
    full = seg(R.init_relay_state(rg.vr, sn), jnp.int32(rg.vr))
    st = R.init_relay_state(rg.vr, sn)
    while bool(st.changed) and int(st.level) < rg.vr:
        st = seg(st, jnp.int32(int(st.level) + 2))
    full, st = jax.device_get((full, st))
    np.testing.assert_array_equal(st.dist, full.dist)
    np.testing.assert_array_equal(st.parent, full.parent)
    assert int(st.level) == int(full.level)


# ------------------------------------------------------ relay segmentation --
def test_relay_segmented_parity_and_epoch_cleanup(eng, golden, tmp_path):
    mgr = _mgr(tmp_path, k=2)
    res, curve = eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    _assert_identical(res, curve, golden)
    assert mgr.report()["epochs_written"] >= 2
    # A finished traversal clears its epochs — a later same-config run
    # starts fresh instead of resuming a finished carry.
    assert mgr.epochs() == []


def test_relay_segmented_with_disabled_store(eng, golden, tmp_path):
    mgr = SuperstepCheckpointer(tmp_path, {"t": 1}, cfg=CkptConfig("off"))
    res = eng.run_segmented(SOURCE, ckpt=mgr)
    _assert_identical(res, None, golden)
    assert list(tmp_path.iterdir()) == []  # nothing touched disk


def _interrupt(eng, tmp_path, boundary: int, k: int = 1, config=None):
    """Run segmented until a raise at the nth superstep boundary; leaves
    epochs on disk."""
    os.environ["BFS_TPU_FAULT"] = f"raise:superstep:{boundary}"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            eng.run_segmented(
                SOURCE, ckpt=_mgr(tmp_path, k=k, config=config),
                telemetry=True,
            )
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()


@pytest.mark.chaos
def test_relay_kill_resume_bit_identical(eng, golden, tmp_path):
    """THE in-process traversal-chaos smoke (ci_gate stage): kill one
    mid-traversal segment, resume, assert bit-identity incl. the
    direction schedule."""
    _interrupt(eng, tmp_path, boundary=2)
    mgr = _mgr(tmp_path, k=1)
    res, curve = eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    assert mgr.report()["resumed_from_epoch"] == 2
    _assert_identical(res, curve, golden)


@pytest.mark.parametrize("mode", ["truncate", "flip"])
def test_corruption_newest_epoch_falls_back_to_previous(
    eng, golden, tmp_path, mode
):
    _interrupt(eng, tmp_path, boundary=3)
    mgr = _mgr(tmp_path, k=1)
    eps = mgr.epochs()
    assert len(eps) == 2  # retention window
    corrupt_file(mgr._epoch_path(eps[-1]), mode=mode)
    res, curve = eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    rep = mgr.report()
    assert rep["resumed_from_epoch"] == eps[-2]
    assert rep["epochs_corrupt_skipped"] >= 1
    _assert_identical(res, curve, golden)


def test_corruption_all_epochs_falls_back_to_fresh(eng, golden, tmp_path):
    _interrupt(eng, tmp_path, boundary=3)
    mgr = _mgr(tmp_path, k=1)
    for ep in mgr.epochs():
        corrupt_file(mgr._epoch_path(ep), mode="flip")
    res, curve = eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    rep = mgr.report()
    # No wrong answers, and the counters NAME the fallback.
    assert rep["resumed_from_epoch"] is None
    assert rep["fresh_fallbacks"] == 1
    assert rep["epochs_corrupt_skipped"] >= 2
    _assert_identical(res, curve, golden)


def test_epoch_missing_carry_keys_falls_back_fresh(eng, golden, tmp_path):
    """The config key does not encode telemetry: an epoch written by a
    telemetry-OFF drive of the same config must make a telemetry-ON
    resume fall back to a fresh traversal (restore gate key check) —
    never KeyError mid-restore."""
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            eng.run_segmented(SOURCE, ckpt=_mgr(tmp_path, k=1))  # no telem
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = _mgr(tmp_path, k=1)
    res, curve = eng.run_segmented(SOURCE, ckpt=mgr, telemetry=True)
    assert mgr.resumed_from_epoch is None  # did NOT resume
    _assert_identical(res, curve, golden)


def test_foreign_config_epoch_is_skipped(eng, golden, tmp_path):
    """An epoch written by a DIFFERENT run config must never feed a
    resume, even if a file lands under this config's stem."""
    _interrupt(eng, tmp_path, boundary=2, config={"other": "run"})
    other = _mgr(tmp_path, k=1, config={"other": "run"})
    mine = _mgr(tmp_path, k=1, config={"mine": "run"})
    for ep in other.epochs():
        os.rename(other._epoch_path(ep), mine._epoch_path(ep))
    assert mine.load_latest() is None
    assert mine.counters["epochs_corrupt_skipped"] >= 1
    res, curve = eng.run_segmented(
        SOURCE, ckpt=_mgr(tmp_path, k=2, config={"mine": "run"}),
        telemetry=True,
    )
    _assert_identical(res, curve, golden)


# ------------------------------------------------------------- multisource --
def test_multi_segmented_parity_and_resume(graph, tmp_path):
    from bfs_tpu.models.multisource import bfs_multi

    sources = [3, 10, 17, 24]
    ref = bfs_multi(graph, sources, engine="push")
    res = run_multi_segmented(
        graph, sources, ckpt=_mgr(tmp_path / "a", k=2), engine="push"
    )
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)
    assert res.num_levels == ref.num_levels

    os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            run_multi_segmented(
                graph, sources, ckpt=_mgr(tmp_path / "b", k=1),
                engine="push",
            )
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = _mgr(tmp_path / "b", k=1)
    res2 = run_multi_segmented(graph, sources, ckpt=mgr, engine="push")
    assert mgr.report()["resumed_from_epoch"] is not None
    np.testing.assert_array_equal(res2.dist, ref.dist)
    np.testing.assert_array_equal(res2.parent, ref.parent)


def test_multi_segmented_pull_parity(graph, tmp_path):
    from bfs_tpu.models.multisource import bfs_multi

    sources = [3, 10]
    ref = bfs_multi(graph, sources, engine="pull")
    res = run_multi_segmented(
        graph, sources, ckpt=_mgr(tmp_path, k=3), engine="pull"
    )
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)


# ----------------------------------------------------------------- sharded --
@pytest.fixture(scope="module")
def sharded_setup():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual platform")
    from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh

    g = rmat_graph(7, 4, seed=3)
    mesh = make_mesh(graph=8, batch=1)
    ref, refc = bfs_sharded(
        g, SOURCE, mesh=mesh, engine="relay", telemetry=True,
        direction="auto", exchange="auto",
    )
    return g, mesh, ref, refc


def _run_sharded_seg(setup, tmp_path, k=2):
    from bfs_tpu.parallel.sharded import bfs_sharded_segmented

    g, mesh, _ref, _refc = setup
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": 1}, cfg=CkptConfig("every", k), shards=8
    )
    res, curve = bfs_sharded_segmented(
        g, SOURCE, mesh=mesh, ckpt=mgr, telemetry=True,
        direction="auto", exchange="auto",
    )
    return mgr, res, curve


def _assert_sharded_identical(res, curve, setup):
    _g, _mesh, ref, refc = setup
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)
    assert (
        curve["direction_schedule"]["schedule"]
        == refc["direction_schedule"]["schedule"]
    )
    # The exchange-arm sequence AND the per-level wire bytes are part of
    # the bit-identity contract — the resumed run re-runs the SAME
    # exchange it would have run uninterrupted.
    assert curve["exchange"]["schedule"] == refc["exchange"]["schedule"]
    assert (
        curve["exchange"]["bytes_per_level"]
        == refc["exchange"]["bytes_per_level"]
    )


def test_sharded_segmented_parity(sharded_setup, tmp_path):
    mgr, res, curve = _run_sharded_seg(sharded_setup, tmp_path, k=2)
    _assert_sharded_identical(res, curve, sharded_setup)
    assert mgr.report()["shards"] == 8


@pytest.mark.chaos
def test_sharded_kill_resume_and_shard_loss(sharded_setup, tmp_path):
    """Kill mid-traversal, then LOSE one shard's file of the newest
    epoch: the loader must fall back to the last COMPLETE epoch and the
    resumed run (on a freshly built mesh) must still be bit-identical —
    the shard-loss recovery state machine."""
    from bfs_tpu.parallel.sharded import bfs_sharded_segmented, make_mesh

    g, _mesh, _ref, _refc = sharded_setup
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:3"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            _run_sharded_seg(sharded_setup, tmp_path, k=1)
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": 1}, cfg=CkptConfig("every", 1), shards=8
    )
    eps = mgr.epochs()
    assert len(eps) == 2
    # Shard loss: damage one shard of the NEWEST epoch only.
    corrupt_file(mgr._epoch_path(eps[-1], shard=5), mode="truncate")
    res, curve = bfs_sharded_segmented(
        g, SOURCE, mesh=make_mesh(graph=8, batch=1), ckpt=mgr,
        telemetry=True, direction="auto", exchange="auto",
    )
    rep = mgr.report()
    assert rep["resumed_from_epoch"] == eps[-2]
    assert rep["epochs_corrupt_skipped"] >= 1
    _assert_sharded_identical(res, curve, sharded_setup)


def test_sharded_rejects_wrong_shard_count(sharded_setup, tmp_path):
    from bfs_tpu.parallel.sharded import bfs_sharded_segmented

    g, mesh, _ref, _refc = sharded_setup
    with pytest.raises(ValueError, match="shards"):
        bfs_sharded_segmented(
            g, SOURCE, mesh=mesh,
            ckpt=SuperstepCheckpointer(
                tmp_path, {"t": 1}, cfg=CkptConfig("every", 1), shards=2
            ),
        )


# ------------------------------------------------------------ fault family --
def test_superstep_fault_spec_parsing():
    assert fault_spec("kill:superstep:3") == ("kill", "superstep", 3)
    assert fault_spec("raise:superstep") == ("raise", "superstep", 1)
    # Exact-boundary spelling still works through the generic machinery.
    assert fault_spec("raise:superstep:0") == ("raise", "superstep:0", 1)


def test_superstep_fault_fires_at_nth_boundary(monkeypatch):
    monkeypatch.setenv("BFS_TPU_FAULT", "raise:superstep:3")
    faults.reset()
    faults.fault_point("superstep:4")   # arrival 1 (family match)
    faults.fault_point("superstep:8")   # arrival 2
    faults.fault_point("unrelated")     # no match, no count
    with pytest.raises(FaultInjected):
        faults.fault_point("superstep:12")  # arrival 3 fires
    faults.reset()


def test_save_epoch_marks_boundary_even_when_disabled(
    tmp_path, monkeypatch
):
    """The fault boundary exists on the off arm too (a segmented test
    run without a store still has killable boundaries)."""
    monkeypatch.setenv("BFS_TPU_FAULT", "raise:superstep")
    faults.reset()
    mgr = SuperstepCheckpointer(tmp_path, {"t": 1}, cfg=CkptConfig("off"))
    with pytest.raises(FaultInjected):
        mgr.save_epoch(1, {})
    faults.reset()


# ------------------------------------------------------------------- serve --
def test_serve_runner_is_segmented_only_when_enabled(graph, monkeypatch):
    from bfs_tpu.serve.executor import SegmentedBatchRunner, build_batch_runner
    from bfs_tpu.serve.registry import GraphRegistry

    reg = GraphRegistry()
    reg.register("g", graph)
    monkeypatch.delenv("BFS_TPU_CKPT", raising=False)
    off = build_batch_runner(reg, "g", "pull", 4)
    assert not isinstance(off, SegmentedBatchRunner)
    monkeypatch.setenv("BFS_TPU_CKPT", "every:2")
    on = build_batch_runner(reg, "g", "pull", 4)
    assert isinstance(on, SegmentedBatchRunner)
    # Parity: the segmented runner's replies are bit-identical.
    sources = np.asarray([3, 10, 17, 24], np.int32)
    a = off(sources)
    b = on(sources)
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)
    assert on.ckpt_progress() is None  # finished: epochs cleared


@pytest.mark.chaos
def test_serve_hung_call_resumes_from_checkpoint(monkeypatch):
    """A wedged mid-traversal device tick (watchdog HungCallError) must
    RESUME from the newest in-process checkpoint epoch on each retry —
    the tick completes device-side (status ok) even though every attempt
    wedges, because each attempt advances at least one segment."""
    import time

    from bfs_tpu.graph.csr import Graph
    from bfs_tpu.oracle.bfs import queue_bfs
    from bfs_tpu.serve import BfsServer

    monkeypatch.setenv("BFS_TPU_CKPT", "every:2")
    v = 24
    g = Graph.from_undirected_edges(
        v, np.array([(i, i + 1) for i in range(v - 1)])
    )
    faults.reset()
    try:
        with BfsServer(
            engine="pull", max_batch=4, tick_s=0.0,
            watchdog_s=0.3, watchdog_min_s=0.2,
            watchdog_compile_floor_s=120.0,
        ) as server:
            server.register("g", g)
            warm = server.submit("g", [0]).result(timeout=120)
            assert warm.record.status == "ok"
            os.environ["BFS_TPU_FAULT"] = "delay:serve.segment:0.8"
            t0 = time.monotonic()
            reply = server.submit("g", [1]).result(timeout=120)
            assert time.monotonic() - t0 < 100
            os.environ.pop("BFS_TPU_FAULT", None)
            assert reply.record.status == "ok"
            np.testing.assert_array_equal(reply.dist, queue_bfs(g, 1)[0])
            counters = server.report()["counters"]
            assert counters.get("ckpt_hung_resumes", 0) >= 1
            assert counters.get("watchdog_timeouts", 0) >= 1
            assert counters.get("ckpt_resumes", 0) >= 1
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()


# ------------------------------------------------------------------- bench --
@pytest.mark.slow
def test_bench_ships_superstep_ckpt_detail(tmp_path):
    """A relay bench with BFS_TPU_CKPT enabled measures the checkpoint
    arm and ships details.superstep_ckpt (overhead + bit-identity) in
    the capture."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu", BENCH_SCALE="8", BENCH_EDGE_FACTOR="4",
        BENCH_ROOTS="2", BENCH_REPEATS="1", BENCH_ENGINE="relay",
        BENCH_TIME_BUDGET="500", BENCH_STEP_PROFILE="0",
        BENCH_PHASE_LEDGER="0", BENCH_LEVEL_CURVE="0",
        BFS_TPU_CKPT="every:2",
        BFS_TPU_JOURNAL_DIR=str(tmp_path / "journal"),
        BFS_TPU_CACHE_DIR=str(tmp_path / "cache"),
    )
    env.pop("BFS_TPU_FAULT", None)
    proc = subprocess.run(
        [sys.executable, "-m", "bfs_tpu.bench"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=500,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    lines = [
        json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith("{")
    ]
    detail = lines[-1]["details"]["superstep_ckpt"]
    assert detail["mode"] == "every" and detail["interval"] == 2
    assert detail["bit_identical"] is True
    assert detail["epochs_written"] >= 1
    assert detail["overhead_ratio"] > 0
    # Epoch sidecars land next to the journal, content-keyed.
    assert not list((tmp_path / "journal").glob("ckpt_*.epoch*.npz")), (
        "finished traversal must clear its epochs"
    )


# -------------------------------------------------------------- chaos CLI --
@pytest.mark.chaos
@pytest.mark.slow
def test_traversal_chaos_cli_relay():
    """One real SIGKILL-at-superstep-boundary iteration through the
    subprocess driver (the full matrix runs in tools/chaos_run.py)."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "tools", "chaos_run.py"),
            "--mode", "traversal", "--iterations", "1", "--seed", "1",
            "--traversal-configs", "relay", "--timeout", "400",
        ],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=560,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
