"""Beyond-HBM streamed traversal (ISSUE 18): host store geometry, the
hoisted demand predicate vs the kernel's in-kernel early-out, cache
pathology (eviction under a one-superblock budget, corrupt device bytes
re-fetched and counted), and the acceptance core — streamed dist/parent
and direction schedule BIT-IDENTICAL to the resident mxu and gather arms
under a budget small enough to force real eviction — plus checkpointed
kill-boundary resume honesty with a cold cache.

Fixture shapes mirror tests/test_expansion_mxu.py: a STAR (hub
explosion), a PATH deeper than the packed 62-level cap, a G(n,m) whose
ramp makes the Beamer predicate actually switch, and an R-MAT (skewed
degrees, scrambled relabel keys).  The multi-superblock cases build on a
>16K-vertex G(n,m): ``vtp`` rounds up to 16384-vertex superblocks, so
anything smaller is a single superblock and can never evict."""

import numpy as np
import pytest

from bfs_tpu.graph import adj_tiles as AT
from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.generators import gnm_graph, path_graph, rmat_graph
from bfs_tpu.models.bfs import RelayEngine
from bfs_tpu.stream import HostTileStore, SuperblockCache, demand_set
from bfs_tpu.stream.prefetch import frontier_blocks, iter_prefetched
from bfs_tpu.stream.store import superblock_fingerprint

SOURCE = 3


def star_graph(n: int = 256) -> Graph:
    hub = np.zeros(n - 1, np.int32)
    leaves = np.arange(1, n, dtype=np.int32)
    return Graph(n, np.concatenate([hub, leaves]),
                 np.concatenate([leaves, hub]))


@pytest.fixture(scope="module")
def gnm():
    return gnm_graph(1 << 10, 3 << 10, seed=5)


@pytest.fixture(scope="module")
def big_gnm():
    """>16K vertices -> multiple column superblocks (the eviction shapes)."""
    return gnm_graph(1 << 15, 1 << 17, seed=11)


@pytest.fixture(scope="module")
def big_engines(big_gnm):
    """(streamed mxu, resident mxu, gather) engines over one relay graph
    build — module-scoped: three engines' programs are the expensive part
    of this file."""
    stream_eng = RelayEngine(big_gnm, expansion="mxu", direction="auto",
                             tiles_mode="stream")
    resident_eng = RelayEngine(stream_eng.relay_graph, expansion="mxu",
                               direction="auto")
    gather_eng = RelayEngine(stream_eng.relay_graph, expansion="gather",
                             direction="auto")
    return stream_eng, resident_eng, gather_eng


def assert_same(a, b):
    np.testing.assert_array_equal(a.dist, b.dist)
    np.testing.assert_array_equal(a.parent, b.parent)
    assert a.num_levels == b.num_levels


# ---------------------------------------------------------------------------
# Host store geometry.
# ---------------------------------------------------------------------------

def test_store_covers_layout_exactly(gnm):
    eng = RelayEngine(gnm, expansion="mxu")
    at = eng.adj_tiles
    store = HostTileStore(at)
    assert store.num_superblocks == at.vtp // AT.SB_VERTS
    assert sum(
        store.real_tiles(g) for g in range(store.num_superblocks)
    ) == at.nt
    for g in range(store.num_superblocks):
        tiles, row_idx, col_local = store.fetch(g)
        nt_g = store.real_tiles(g)
        lo, hi = AT.sb_span(at, g)
        np.testing.assert_array_equal(tiles[:nt_g], at.tiles[lo:hi])
        np.testing.assert_array_equal(row_idx[:nt_g], at.row_idx[lo:hi])
        np.testing.assert_array_equal(
            col_local[:nt_g],
            np.asarray(at.col_id[lo:hi], np.int32) - g * AT.SB_TILES,
        )
        # Pad tiles are INERT: zero bits, the guaranteed-zero frontier
        # pad block, the dropped overflow segment.
        assert not tiles[nt_g:].any()
        assert (row_idx[nt_g:] == at.rtp // AT.TILE).all()
        assert (col_local[nt_g:] == AT.SB_TILES).all()
        # pow2 padding (the compile-count bound) and honest accounting.
        assert store.pad_tiles(g) & (store.pad_tiles(g) - 1) == 0
        assert store.sb_bytes(g) == (
            tiles.nbytes + row_idx.nbytes + col_local.nbytes
        )


def test_store_fingerprint_is_content_addressed(gnm):
    eng = RelayEngine(gnm, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    tiles, row_idx, col_local = store.fetch(0)
    assert store.fingerprint(0) == superblock_fingerprint(
        tiles, row_idx, col_local
    )
    bad = tiles.copy()
    bad[0, 0, 0] ^= 1
    assert superblock_fingerprint(
        bad, row_idx, col_local
    ) != store.fingerprint(0)


# ---------------------------------------------------------------------------
# Demand set == the kernel's per-tile early-out predicate, hoisted.
# ---------------------------------------------------------------------------

def _brute_force_demand(at, fwords):
    """The in-kernel predicate, literally: tile t is live iff its 4-word
    frontier block is nonzero; superblock g is demanded iff any of its
    REAL tiles is live."""
    blocks = frontier_blocks(fwords, at.rtp)
    live_tiles = blocks[np.asarray(at.row_idx[: at.nt])].any(axis=1)
    out = []
    for g in range(at.vtp // AT.SB_VERTS):
        lo, hi = AT.sb_span(at, g)
        if live_tiles[lo:hi].any():
            out.append(g)
    return np.asarray(out, dtype=np.int32)


@pytest.mark.parametrize("maker", [
    lambda: star_graph(),
    lambda: path_graph(300),
    lambda: gnm_graph(1 << 10, 3 << 10, seed=5),
    lambda: rmat_graph(8, 8, seed=7),
])
def test_demand_matches_in_kernel_early_out(maker):
    g = maker()
    eng = RelayEngine(g, expansion="mxu")
    at = eng.adj_tiles
    store = HostTileStore(at)
    rng = np.random.default_rng(3)
    nwords = at.rows // 32 + (1 if at.rows % 32 else 0)
    cases = [
        np.zeros(nwords, np.uint32),                      # empty frontier
        np.zeros(nwords, np.uint32),                      # single source bit
        rng.integers(0, 1 << 32, nwords, dtype=np.uint32),  # dense
        (rng.integers(0, 1 << 32, nwords, dtype=np.uint32)
         * (rng.random(nwords) < 0.1)).astype(np.uint32),   # sparse words
    ]
    cases[1][0] = 1
    for fwords in cases:
        np.testing.assert_array_equal(
            demand_set(store, fwords), _brute_force_demand(at, fwords)
        )


def test_empty_superblock_never_demanded():
    # A path graph reaches few columns; force an all-ones frontier and
    # check only superblocks with real tiles appear.
    g = path_graph(300)
    eng = RelayEngine(g, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    fwords = np.full(eng.adj_tiles.rows // 32 + 1, 0xFFFFFFFF, np.uint32)
    for gg in demand_set(store, fwords):
        assert store.real_tiles(int(gg)) > 0


# ---------------------------------------------------------------------------
# Cache pathology.
# ---------------------------------------------------------------------------

def test_cache_eviction_under_one_superblock_budget(big_engines):
    stream_eng, _, _ = big_engines
    store = HostTileStore(stream_eng.adj_tiles)
    assert store.num_superblocks >= 2, "eviction shape needs >=2 superblocks"
    budget = max(
        store.sb_bytes(g) for g in range(store.num_superblocks)
    )
    cache = SuperblockCache(store, budget_bytes=budget)
    demanded = [
        g for g in range(store.num_superblocks) if store.real_tiles(g)
    ]
    for g in demanded:        # cold sweep: all misses
        cache.get(g)
    for g in demanded:        # second sweep under a 1-superblock budget
        cache.get(g)
    assert cache.misses >= len(demanded)
    assert cache.evictions > 0
    assert cache.resident_bytes() <= budget
    assert cache.bytes_streamed >= sum(store.sb_bytes(g) for g in demanded)
    rep = cache.report()
    assert rep["evictions"] == cache.evictions
    assert rep["budget_bytes"] == budget


def test_cache_single_oversized_allowance(gnm):
    eng = RelayEngine(gnm, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    cache = SuperblockCache(store, budget_bytes=1)  # smaller than any slab
    ops = cache.get(0)
    assert cache.resident_bytes() == store.sb_bytes(0)  # in alone
    again = cache.get(0)
    assert again is ops and cache.hits == 1  # still resident, no thrash


def test_cache_hit_returns_same_buffers(gnm):
    eng = RelayEngine(gnm, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    cache = SuperblockCache(store, budget_bytes=1 << 30)
    a = cache.get(0)
    b = cache.get(0)
    assert a is b
    assert (cache.hits, cache.misses) == (1, 1)
    assert cache.bytes_streamed == store.sb_bytes(0)


def test_corrupt_superblock_refetched_not_crashed(gnm):
    import jax.numpy as jnp

    eng = RelayEngine(gnm, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    cache = SuperblockCache(store, budget_bytes=1 << 30, verify=True)
    cache.get(0)
    key = store.fingerprint(0)
    nbytes, (tiles, row_idx, col_local), g0 = cache._resident[key]
    rotten = np.asarray(tiles).copy()
    rotten[0, 0, 0] ^= 1  # a single flipped bit in HBM
    cache._resident[key] = (
        nbytes, (jnp.asarray(rotten), row_idx, col_local), g0
    )
    fresh = cache.get(0)
    assert cache.corrupt_refetches == 1
    assert cache.misses == 2  # the re-fetch is an honest miss
    # The served operands are the host truth again, not the rotten bytes.
    np.testing.assert_array_equal(np.asarray(fresh[0]), store.fetch(0)[0])
    # And a verified clean hit does not count as corrupt.
    cache.get(0)
    assert cache.corrupt_refetches == 1


def test_stream_verify_env_knob(monkeypatch, gnm):
    from bfs_tpu.stream.cache import stream_verify_enabled

    monkeypatch.delenv("BFS_TPU_STREAM_VERIFY", raising=False)
    assert stream_verify_enabled() is False
    monkeypatch.setenv("BFS_TPU_STREAM_VERIFY", "1")
    assert stream_verify_enabled() is True
    assert stream_verify_enabled(False) is False  # explicit arg wins


def test_iter_prefetched_order_and_coverage(gnm):
    eng = RelayEngine(gnm, expansion="mxu")
    store = HostTileStore(eng.adj_tiles)
    cache = SuperblockCache(store, budget_bytes=1 << 30)
    demand = np.asarray(
        [g for g in range(store.num_superblocks) if store.real_tiles(g)],
        np.int32,
    )
    seen = [g for g, _ops in iter_prefetched(cache, demand)]
    assert seen == [int(g) for g in demand]
    assert list(iter_prefetched(cache, np.asarray([], np.int32))) == []


# ---------------------------------------------------------------------------
# Knob surface.
# ---------------------------------------------------------------------------

def test_tiles_mode_knob(monkeypatch):
    from bfs_tpu.ops import relay_mxu as MX

    monkeypatch.delenv("BFS_TPU_TILES", raising=False)
    assert MX.resolve_tiles_mode() == "resident"
    monkeypatch.setenv("BFS_TPU_TILES", "stream")
    assert MX.resolve_tiles_mode() == "stream"
    assert MX.resolve_tiles_mode("auto") == "auto"  # arg wins
    monkeypatch.setenv("BFS_TPU_TILES", "paged")
    with pytest.raises(ValueError):
        MX.resolve_tiles_mode()
    monkeypatch.setenv("BFS_TPU_STREAM_CACHE_GB", "0.5")
    assert MX.stream_cache_budget_bytes() == (1 << 30) // 2


def test_stream_requires_mxu_arm(gnm):
    eng = RelayEngine(gnm, expansion="gather")
    assert not eng._stream_effective()
    with pytest.raises(ValueError, match="mxu"):
        eng.run_streamed(SOURCE)


def test_auto_mode_streams_only_over_budget(monkeypatch, gnm):
    eng = RelayEngine(gnm, expansion="mxu", tiles_mode="auto")
    monkeypatch.setenv("BFS_TPU_STREAM_CACHE_GB", "1")
    assert not eng._stream_effective()  # tiny layout fits easily
    monkeypatch.setenv(
        "BFS_TPU_STREAM_CACHE_GB", str(eng.adj_tiles.nbytes / 2 / (1 << 30))
    )
    assert eng._stream_effective()  # layout outgrew the budget


# ---------------------------------------------------------------------------
# Bit-identity: streamed == resident mxu == gather, eviction forced.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("maker", [
    lambda: star_graph(),
    lambda: path_graph(300),   # > packed cap: exercises unpacked rerun
    lambda: gnm_graph(1 << 10, 3 << 10, seed=5),
    lambda: rmat_graph(8, 8, seed=7),
])
def test_streamed_matches_resident_small_shapes(maker):
    g = maker()
    resident = RelayEngine(g, expansion="mxu", direction="auto")
    streamed = RelayEngine(resident.relay_graph, expansion="mxu",
                           direction="auto", tiles_mode="stream")
    assert_same(resident.run(SOURCE), streamed.run(SOURCE))


def test_streamed_bit_identical_under_forced_eviction(big_engines):
    """THE acceptance core: a cache budget of one max superblock forces
    real eviction + host re-fetch mid-traversal, and dist/parent + the
    direction schedule still match the resident mxu arm AND the gather
    arm bit-for-bit."""
    stream_eng, resident_eng, gather_eng = big_engines
    store = HostTileStore(stream_eng.adj_tiles)
    budget = max(
        store.sb_bytes(g) for g in range(store.num_superblocks)
    )
    s_res, s_curve = stream_eng.run_streamed(
        SOURCE, telemetry=True, cache_budget_bytes=budget
    )
    ledger = stream_eng.stream_report
    assert ledger["evictions"] > 0, "budget failed to force eviction"
    assert ledger["bytes_streamed"] > 0
    r_res, r_curve = resident_eng.run_segmented(
        SOURCE, ckpt=_off_ckpt(), telemetry=True
    )
    g_res, g_curve = gather_eng.run_segmented(
        SOURCE, ckpt=_off_ckpt(), telemetry=True
    )
    assert_same(s_res, r_res)
    assert_same(s_res, g_res)
    assert (
        s_curve["direction_schedule"]["schedule"]
        == r_curve["direction_schedule"]["schedule"]
        == g_curve["direction_schedule"]["schedule"]
    )
    # The per-level ledger is internally consistent: totals are the sum
    # of the per-level deltas, and only pull levels stream bytes.
    rows = ledger["levels"]
    assert sum(r["bytes_streamed"] for r in rows) == ledger["bytes_streamed"]
    assert all(
        r["bytes_streamed"] == 0 for r in rows if r["arm"] == "push"
    )


def test_stream_ledger_on_engine_run_routing(big_engines):
    """run() on a stream-mode engine takes the streamed path and leaves
    the ledger behind."""
    stream_eng, resident_eng, _ = big_engines
    res = stream_eng.run(SOURCE)
    assert_same(res, resident_eng.run(SOURCE))
    assert stream_eng.stream_report["misses"] >= 1


# ---------------------------------------------------------------------------
# Checkpoint resume with a cold cache.
# ---------------------------------------------------------------------------

def _off_ckpt():
    import tempfile

    from bfs_tpu.resilience.superstep_ckpt import (
        CkptConfig,
        SuperstepCheckpointer,
    )

    return SuperstepCheckpointer(
        tempfile.mkdtemp(prefix="stream_off_"), {"t": 1},
        cfg=CkptConfig("off"),
    )


def _mgr(tmp_path, k=1):
    from bfs_tpu.resilience.superstep_ckpt import (
        CkptConfig,
        SuperstepCheckpointer,
    )

    return SuperstepCheckpointer(
        str(tmp_path), {"cfg": "stream-test"},
        cfg=CkptConfig(mode="every", k=k),
    )


def test_streamed_resume_from_epoch_cold_cache(gnm, tmp_path):
    """Interrupt a checkpointed streamed run mid-traversal (fault point
    at a segment boundary), then resume with a FRESH engine — cold HBM
    cache, cold jit caches — and require bit-identical dist/parent +
    direction schedule plus an honest resumed_from_epoch."""
    import os as _os

    from bfs_tpu.resilience import faults
    from bfs_tpu.resilience.faults import FaultInjected

    golden_eng = RelayEngine(gnm, expansion="mxu", direction="auto",
                             tiles_mode="stream")
    golden, golden_curve = golden_eng.run_streamed(SOURCE, telemetry=True)

    eng = RelayEngine(gnm, expansion="mxu", direction="auto",
                      tiles_mode="stream")
    _os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            eng.run_streamed(SOURCE, ckpt=_mgr(tmp_path), telemetry=True)
    finally:
        _os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    resumed_eng = RelayEngine(gnm, expansion="mxu", direction="auto",
                              tiles_mode="stream")
    mgr = _mgr(tmp_path)
    res, curve = resumed_eng.run_streamed(SOURCE, ckpt=mgr, telemetry=True)
    assert mgr.resumed_from_epoch is not None
    assert_same(res, golden)
    assert (
        curve["direction_schedule"]["schedule"]
        == golden_curve["direction_schedule"]["schedule"]
    )
    assert mgr.epochs() == []  # cleared on completion


def test_streamed_and_segmented_epochs_interchange(gnm, tmp_path):
    """The carry keys are the segment program's own: an epoch written by
    the SEGMENTED runner resumes a STREAMED run bit-identically."""
    import os as _os

    from bfs_tpu.resilience import faults
    from bfs_tpu.resilience.faults import FaultInjected

    resident = RelayEngine(gnm, expansion="mxu", direction="auto")
    golden = resident.run(SOURCE)
    _os.environ["BFS_TPU_FAULT"] = "raise:superstep:2"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            resident.run_segmented(SOURCE, ckpt=_mgr(tmp_path))
    finally:
        _os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    streamed = RelayEngine(gnm, expansion="mxu", direction="auto",
                           tiles_mode="stream")
    mgr = _mgr(tmp_path)
    res = streamed.run_streamed(SOURCE, ckpt=mgr)
    assert mgr.resumed_from_epoch is not None
    assert_same(res, golden)


# ---------------------------------------------------------------------------
# Telemetry ledger shape.
# ---------------------------------------------------------------------------

def test_stream_report_shape():
    from bfs_tpu.obs.telemetry import stream_report

    rows = [
        {"level": 1, "arm": "push", "demanded": 0, "bytes_streamed": 0,
         "hits": 0, "misses": 0, "evictions": 0, "corrupt_refetches": 0},
        {"level": 2, "arm": "pull", "demanded": 2, "bytes_streamed": 64,
         "hits": 1, "misses": 2, "evictions": 1, "corrupt_refetches": 0},
    ]
    doc = stream_report(
        rows, budget_bytes=128,
        store={"num_superblocks": 2, "real_tiles": 4,
               "host_store_bytes": 256, "max_superblock_bytes": 128},
        cache={"hits": 5, "misses": 9},
    )
    assert doc["budget_bytes"] == 128
    assert doc["bytes_streamed"] == 64 and doc["evictions"] == 1
    assert doc["levels"] == rows and doc["levels"] is not rows
    assert doc["cache"]["misses"] == 9
    import json

    json.dumps(doc)  # JSON-ready end to end
