"""Serving-layer tests: every served result is oracle-checked (distances
bit-exact vs queue_bfs, parents canonical min-parent / check() invariants),
plus the batching, deadline, cache, and degradation semantics from the
serve subsystem's contract."""

import threading
import time

import numpy as np
import pytest

from bfs_tpu.graph.generators import gnm_graph, path_graph
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.serve import (
    AdmissionError,
    BfsServer,
    GraphRegistry,
    QueryTimeout,
    ServerClosed,
)

TIMEOUT = 300  # generous future.result bound; CPU compiles are seconds


@pytest.fixture(scope="module")
def served_graph():
    return gnm_graph(150, 400, seed=11)


@pytest.fixture(scope="module")
def server(served_graph):
    with BfsServer(max_batch=8) as srv:
        srv.register("g", served_graph)
        yield srv


def test_single_source_oracle_parity(server, served_graph):
    for s in (0, 7, 149):
        reply = server.query("g", s).result(TIMEOUT)
        d, _ = queue_bfs(served_graph, s)
        _, p = canonical_bfs(served_graph, s)
        np.testing.assert_array_equal(reply.dist, d)
        np.testing.assert_array_equal(reply.parent, p)
        assert check(served_graph, reply.dist, reply.parent, s) == []


def test_multi_source_collapse_parity(server, served_graph):
    srcs = [3, 77, 140]
    reply = server.query_multi("g", srcs).result(TIMEOUT)
    od, _ = queue_bfs(served_graph, srcs)
    np.testing.assert_array_equal(reply.dist, od)
    assert check(served_graph, reply.dist, reply.parent, srcs) == []


def test_multi_source_tree_rows_match_single(server, served_graph):
    srcs = [5, 60]
    reply = server.query_multi("g", srcs, collapse=False).result(TIMEOUT)
    assert reply.dist.shape == (2, served_graph.num_vertices)
    for i, s in enumerate(srcs):
        d, _ = queue_bfs(served_graph, s)
        _, p = canonical_bfs(served_graph, s)
        np.testing.assert_array_equal(reply.dist[i], d)
        np.testing.assert_array_equal(reply.parent[i], p)


def test_batch_coalescing_across_concurrent_submitters(server):
    # Stage concurrent submitters while batching is held, then release:
    # all requests must ride ONE device batch.
    server.pause()
    futs = {}
    threads = []

    def submit(s):
        futs[s] = server.query("g", s)

    for s in range(100, 106):
        t = threading.Thread(target=submit, args=(s,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    batches_before = server.metrics.count("batches")
    server.resume()
    replies = [futs[s].result(TIMEOUT) for s in futs]
    assert server.metrics.count("batches") == batches_before + 1
    assert {r.record.batch_size for r in replies} == {8}  # 6 -> bucket 8
    assert all(r.record.queue_wait_s >= 0 for r in replies)


def test_executable_cache_hit_on_second_same_shape_batch(served_graph):
    with BfsServer(max_batch=4, result_cache_size=0) as srv:
        srv.register("g", served_graph)
        first = srv.query("g", 1).result(TIMEOUT)
        assert first.record.compile_hit is False
        second = srv.query("g", 2).result(TIMEOUT)
        assert second.record.compile_hit is True
        assert srv.exe_cache.hits == 1 and srv.exe_cache.misses == 1


def test_result_lru_cache_serves_repeats(served_graph):
    with BfsServer(max_batch=4) as srv:
        srv.register("g", served_graph)
        r1 = srv.query("g", 9).result(TIMEOUT)
        r2 = srv.query("g", 9).result(TIMEOUT)
        assert r1.record.status == "ok"
        assert r2.record.status == "result_cache"
        np.testing.assert_array_equal(r1.dist, r2.dist)
        np.testing.assert_array_equal(r1.parent, r2.parent)


def test_deadline_expiry_returns_timeout_not_wrong_answer(server):
    server.pause()
    expired = server.query("g", 120, timeout_s=0.0)
    live = server.query("g", 121, timeout_s=60.0)
    time.sleep(0.02)  # let the zero deadline pass before batch formation
    server.resume()
    with pytest.raises(QueryTimeout):
        expired.result(TIMEOUT)
    reply = live.result(TIMEOUT)  # the live request still gets its answer
    assert reply.record.status == "ok"
    d, _ = queue_bfs(server.registry.get("g").graph, 121)
    np.testing.assert_array_equal(reply.dist, d)


def test_admission_queue_backpressure(served_graph):
    with BfsServer(max_batch=4, queue_depth=2, result_cache_size=0) as srv:
        srv.register("g", served_graph)
        srv.pause()
        srv.query("g", 1)
        srv.query("g", 2)
        with pytest.raises(AdmissionError):
            srv.query("g", 3)
        assert srv.metrics.count("rejected") == 1
        srv.resume()


def test_oracle_degradation_for_tiny_graphs(tiny_graph):
    with BfsServer(oracle_max_vertices=100) as srv:
        srv.register("t", tiny_graph)
        reply = srv.query("t", 0).result(TIMEOUT)
        assert reply.record.status == "oracle"
        assert reply.dist.tolist() == [0, 1, 1, 2, 2, 1]
        assert reply.parent.tolist() == [0, 0, 0, 2, 2, 0]  # canonical
        # No executable was ever compiled for the degraded path.
        assert len(srv.exe_cache) == 0


def test_second_graph_evicts_first_under_capped_budget(served_graph):
    other = gnm_graph(150, 400, seed=12)
    registry = GraphRegistry(device_budget_bytes=1)
    with BfsServer(registry, max_batch=4) as srv:
        srv.register("a", served_graph)
        srv.register("b", other)
        ra = srv.query("a", 0).result(TIMEOUT)
        pg_a = registry.layout("a", "pull")
        assert getattr(pg_a, "_device_ell", None) is not None
        rb = srv.query("b", 0).result(TIMEOUT)
        # B displaced A via drop_device_operands (asserted on the memo).
        assert getattr(pg_a, "_device_ell", None) is None
        assert registry.resident_keys() == [("b", 0, "pull")]
        assert registry.evictions == 1
        # A still serves correctly after re-upload, reusing its compiled
        # executable (operands are arguments, not baked-in constants).
        ra2 = srv.query("a", 3).result(TIMEOUT)
        assert ra2.record.compile_hit is True
        d, _ = queue_bfs(served_graph, 3)
        np.testing.assert_array_equal(ra2.dist, d)
        assert registry.evictions == 2


def test_push_engine_parity(served_graph):
    with BfsServer(engine="push", max_batch=4) as srv:
        srv.register("g", served_graph)
        reply = srv.query("g", 4).result(TIMEOUT)
        d, _ = queue_bfs(served_graph, 4)
        _, p = canonical_bfs(served_graph, 4)
        np.testing.assert_array_equal(reply.dist, d)
        np.testing.assert_array_equal(reply.parent, p)


def test_relay_engine_parity(served_graph):
    from bfs_tpu.graph.benes import native_available

    if not native_available():
        pytest.skip("native Beneš router unavailable")
    with BfsServer(engine="relay", max_batch=4) as srv:
        srv.register("g", served_graph)
        reply = srv.query("g", 8).result(TIMEOUT)
        d, _ = queue_bfs(served_graph, 8)
        _, p = canonical_bfs(served_graph, 8)
        np.testing.assert_array_equal(reply.dist, d)
        np.testing.assert_array_equal(reply.parent, p)


def test_device_error_degrades_to_oracle(served_graph, monkeypatch):
    import bfs_tpu.serve.server as server_mod

    def boom(*a, **k):
        raise RuntimeError("simulated device failure")

    monkeypatch.setattr(server_mod, "build_batch_runner", boom)
    with BfsServer(max_batch=4) as srv:
        srv.register("g", served_graph)
        reply = srv.query("g", 2).result(TIMEOUT)
        assert reply.record.status == "oracle"
        assert srv.metrics.count("device_errors") == 1
        d, _ = queue_bfs(served_graph, 2)
        np.testing.assert_array_equal(reply.dist, d)


def test_submit_validation(server):
    with pytest.raises(KeyError):
        server.query("nope", 0)
    with pytest.raises(ValueError):
        server.query("g", 150)  # out of range
    with pytest.raises(ValueError):
        server.submit("g", [1, 2], mode="single")
    with pytest.raises(ValueError):
        server.submit("g", [1], mode="bogus")
    with pytest.raises(ValueError):
        server.submit("g", [1], engine="bogus")


def test_close_fails_pending_and_rejects_new(served_graph):
    srv = BfsServer(max_batch=4)
    srv.register("g", served_graph)
    srv.pause()
    fut = srv.query("g", 1)
    srv.close()
    with pytest.raises(ServerClosed):
        fut.result(TIMEOUT)
    with pytest.raises(ServerClosed):
        srv.query("g", 2)


def test_unregister_invalidates_caches(served_graph):
    # Re-registering a DIFFERENT graph under the same name must never be
    # served from executables or result rows computed on the old graph.
    other = gnm_graph(150, 400, seed=13)
    with BfsServer(max_batch=4) as srv:
        srv.register("g", served_graph)
        stale = srv.query("g", 0).result(TIMEOUT)
        srv.unregister("g")
        assert len(srv.exe_cache) == 0
        srv.register("g", other)
        fresh = srv.query("g", 0).result(TIMEOUT)
        assert fresh.record.status == "ok"
        assert fresh.record.result_cache_hit is False
        d, _ = queue_bfs(other, 0)
        np.testing.assert_array_equal(fresh.dist, d)
        assert not np.array_equal(stale.dist, fresh.dist)


def test_deep_graph_supersteps(server):
    # A high-diameter graph through the same serving path: distances must
    # be exact at every level (no truncation at any batching boundary).
    g = path_graph(40)
    server.register("path", g)
    reply = server.query("path", 0).result(TIMEOUT)
    np.testing.assert_array_equal(reply.dist, np.arange(40))
    assert reply.num_levels == 40
