"""2D tile-grid sharding (ISSUE 17): the r x c (row, col) mesh over the
MXU tile space vs the 1D mesh and the single-chip relay.

The contract under test is BIT-IDENTITY, not mere correctness: on the
same 8-shard ShardedRelayGraph the grid engine must reproduce the 1D
run's dist/parent, direction schedule AND column-axis wire story exactly
(the col exchange ships the same new-frontier words the 1D all-gather
ships, so per-level col bytes and the col arm schedule coincide with the
1D curve at any r*c = 8), while the 1x8 degenerate must collapse the row
axis to an identity reduce — zero bytes, arm "none".  The ``grid_smoke``
marker is the parity core tools/ci_gate.sh runs as its own stage.
"""

import os

import jax
import numpy as np
import pytest

from bfs_tpu.graph.generators import (
    gnm_graph,
    path_graph,
    rmat_graph,
    star_graph,
)
from bfs_tpu.graph.grid_layout import (
    grid_tile_placement,
    parse_mesh_spec,
)
from bfs_tpu.graph.relay import build_sharded_relay_graph
from bfs_tpu.oracle.bfs import canonical_bfs, check, queue_bfs
from bfs_tpu.parallel.grid import (
    bfs_grid,
    bfs_grid_segmented,
    make_grid_mesh,
    resolve_grid_mesh,
)
from bfs_tpu.parallel.sharded import bfs_sharded, make_mesh
from bfs_tpu.resilience import faults
from bfs_tpu.resilience.faults import FaultInjected
from bfs_tpu.resilience.superstep_ckpt import (
    CkptConfig,
    SuperstepCheckpointer,
)

pytestmark = pytest.mark.skipif(
    not __import__(
        "bfs_tpu.graph.benes", fromlist=["native_available"]
    ).native_available(),
    reason="native benes router unavailable",
)

SOURCE = 3


def assert_oracle(g, res, s):
    d, _ = queue_bfs(g, s)
    _, p = canonical_bfs(g, s)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert check(g, res.dist, res.parent, s) == []


@pytest.fixture(scope="module")
def gnm():
    return gnm_graph(250, 1273, seed=1)


@pytest.fixture(scope="module")
def srg8(gnm):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual platform")
    return build_sharded_relay_graph(gnm, 8)


@pytest.fixture(scope="module")
def ref_1d(srg8):
    """The 1D x8 golden: same shard layout, auto direction + exchange."""
    return bfs_sharded(
        srg8, SOURCE, mesh=make_mesh(graph=8), engine="relay",
        telemetry=True, direction="auto", exchange="auto",
    )


# ------------------------------------------------------------ mesh spec --
@pytest.mark.grid_smoke
def test_parse_mesh_spec():
    assert parse_mesh_spec("2x4") == (2, 4)
    assert parse_mesh_spec("1x8") == (1, 8)
    assert parse_mesh_spec("8") == (1, 8)  # BENCH_MESH back-compat
    with pytest.raises(ValueError):
        parse_mesh_spec("0x4")
    with pytest.raises(ValueError):
        parse_mesh_spec("2x")
    with pytest.raises(ValueError):
        parse_mesh_spec("grid")


def test_resolve_grid_mesh_env(monkeypatch):
    monkeypatch.setenv("BFS_TPU_MESH", "2x4")
    assert resolve_grid_mesh() == (2, 4)
    monkeypatch.delenv("BFS_TPU_MESH")
    assert resolve_grid_mesh() == (1, len(jax.devices()))
    assert resolve_grid_mesh("4x2") == (4, 2)


def test_make_grid_mesh_too_many_devices():
    with pytest.raises(ValueError, match="devices"):
        make_grid_mesh(4, 4)


# ----------------------------------------------------- single-chip parity --
@pytest.mark.grid_smoke
@pytest.mark.parametrize("shape", [(2, 4), (1, 8), (4, 2), (8, 1)])
def test_grid_matches_oracle_all_shapes(gnm, srg8, shape):
    r, c = shape
    res = bfs_grid(srg8, SOURCE, mesh=make_grid_mesh(r, c))
    assert_oracle(gnm, res, SOURCE)


# ------------------------------------------------- 1D/grid bit-identity --
@pytest.mark.grid_smoke
@pytest.mark.parametrize("shape", [(2, 4), (1, 8)])
def test_grid_bit_identical_to_1d(srg8, ref_1d, shape):
    """dist/parent, direction schedule, and the COLUMN axis's per-level
    bytes + arm schedule must all equal the 1D x8 run's — the col
    exchange ships exactly the words the 1D all-gather ships."""
    r, c = shape
    ref, refc = ref_1d
    res, curve = bfs_grid(
        srg8, SOURCE, mesh=make_grid_mesh(r, c), telemetry=True,
        direction="auto", exchange="auto",
    )
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)
    assert res.num_levels == ref.num_levels
    assert (
        curve["direction_schedule"]["schedule"]
        == refc["direction_schedule"]["schedule"]
    )
    ex = curve["exchange"]
    assert ex["col_schedule"] == refc["exchange"]["schedule"]
    assert ex["col_bytes"] == refc["exchange"]["bytes_per_level"]
    if r == 1:
        # Degenerate row axis: identity reduce, nothing on the wire —
        # the grid at 1x8 IS the 1D engine, bytes included.
        assert all(b == 0 for b in ex["row_bytes"])
        assert all(a == "none" for a in ex["row_schedule"])
        assert ex["total_bytes"] == refc["exchange"]["total_bytes"]
    else:
        # Real row axis: candidates move, and every level's combined
        # per-chip wire stays under the 1D flat all-gather's share.
        assert any(b > 0 for b in ex["row_bytes"])
        assert ex["axes"]["row"]["size"] == r


# ------------------------------------------------------- graph shapes ----
@pytest.mark.grid_smoke
@pytest.mark.parametrize("make", [
    lambda: star_graph(300),
    lambda: path_graph(61),
    lambda: rmat_graph(7, 4, seed=5),
], ids=["star", "path", "rmat"])
def test_grid_graph_shapes(make):
    g = make()
    res = bfs_grid(g, 0, mesh=make_grid_mesh(2, 4))
    assert_oracle(g, res, 0)


@pytest.mark.parametrize("arm", ["flat", "bitmap", "delta", "auto"])
def test_grid_exchange_arms(gnm, srg8, arm):
    res = bfs_grid(
        srg8, SOURCE, mesh=make_grid_mesh(2, 4), exchange=arm
    )
    assert_oracle(gnm, res, SOURCE)


def test_grid_nonzero_source_disconnected():
    g = gnm_graph(200, 220, seed=3)
    res = bfs_grid(g, 137, mesh=make_grid_mesh(2, 4))
    assert_oracle(g, res, 137)
    assert (res.dist == np.iinfo(np.int32).max).any()


@pytest.mark.grid_smoke
def test_grid_packed_fallback_deep_graph():
    """80 levels overflows the 62-level packed word; the truncation
    re-run must deliver the full unpacked traversal."""
    g = path_graph(80)
    res = bfs_grid(g, 0, mesh=make_grid_mesh(2, 4))
    d, p = queue_bfs(g, 0)
    np.testing.assert_array_equal(res.dist, d)
    np.testing.assert_array_equal(res.parent, p)
    assert res.num_levels == 80


# ------------------------------------------------------ tile placement ---
def test_grid_tile_placement_partitions(srg8):
    """Each shard's adjacency tiles partition exactly across its mesh
    column's r cells; 1x8 degenerates to the per-shard tile counts."""
    p24 = grid_tile_placement(srg8, 2, 4)
    assert p24["cells"].shape == (2, 4)
    assert int(p24["cells"].sum()) == p24["total_tiles"]
    p18 = grid_tile_placement(srg8, 1, 8)
    assert p18["cells"].shape == (1, 8)
    assert int(p18["cells"].sum()) == p24["total_tiles"]
    # Column j of the 2x4 placement holds exactly the tiles of the
    # shards b with b % 4 == j (the column-stripe ownership rule).
    col24 = p24["cells"].sum(axis=0)
    col18 = p18["cells"].reshape(8)
    for j in range(4):
        assert col24[j] == col18[j] + col18[j + 4]


# --------------------------------------------------- segmented / resume --
@pytest.fixture(scope="module")
def seg_setup():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual platform")
    g = rmat_graph(7, 4, seed=3)
    mesh = make_grid_mesh(2, 4)
    ref, refc = bfs_grid(
        g, SOURCE, mesh=mesh, telemetry=True,
        direction="auto", exchange="auto",
    )
    return g, mesh, ref, refc


def _run_grid_seg(setup, tmp_path, k=2):
    g, mesh, _ref, _refc = setup
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": 1}, cfg=CkptConfig("every", k), shards=8
    )
    res, curve = bfs_grid_segmented(
        g, SOURCE, mesh=mesh, ckpt=mgr, telemetry=True,
        direction="auto", exchange="auto",
    )
    return mgr, res, curve


def _assert_grid_identical(res, curve, setup):
    _g, _mesh, ref, refc = setup
    np.testing.assert_array_equal(res.dist, ref.dist)
    np.testing.assert_array_equal(res.parent, ref.parent)
    assert (
        curve["direction_schedule"]["schedule"]
        == refc["direction_schedule"]["schedule"]
    )
    # BOTH per-axis wire records are part of the bit-identity contract.
    for k in ("col_schedule", "col_bytes", "row_schedule", "row_bytes"):
        assert curve["exchange"][k] == refc["exchange"][k], k


@pytest.mark.grid_smoke
def test_grid_segmented_parity(seg_setup, tmp_path):
    mgr, res, curve = _run_grid_seg(seg_setup, tmp_path, k=2)
    _assert_grid_identical(res, curve, seg_setup)
    assert mgr.report()["shards"] == 8


@pytest.mark.chaos
def test_grid_kill_resume(seg_setup, tmp_path):
    """Die at superstep boundary 3 with per-cell epochs on disk; the
    resumed run must restore a checkpoint (not restart) and land
    bit-identical, per-axis wire records included."""
    os.environ["BFS_TPU_FAULT"] = "raise:superstep:3"
    faults.reset()
    try:
        with pytest.raises(FaultInjected):
            _run_grid_seg(seg_setup, tmp_path, k=1)
    finally:
        os.environ.pop("BFS_TPU_FAULT", None)
        faults.reset()
    g, mesh, _ref, _refc = seg_setup
    mgr = SuperstepCheckpointer(
        tmp_path, {"t": 1}, cfg=CkptConfig("every", 1), shards=8
    )
    assert len(mgr.epochs()) >= 1
    res, curve = bfs_grid_segmented(
        g, SOURCE, mesh=mesh, ckpt=mgr, telemetry=True,
        direction="auto", exchange="auto",
    )
    assert mgr.report()["resumed_from_epoch"] is not None
    _assert_grid_identical(res, curve, seg_setup)


def test_grid_segmented_rejects_wrong_shard_count(seg_setup, tmp_path):
    g, mesh, _ref, _refc = seg_setup
    with pytest.raises(ValueError, match="shards"):
        bfs_grid_segmented(
            g, SOURCE, mesh=mesh,
            ckpt=SuperstepCheckpointer(
                tmp_path, {"t": 1}, cfg=CkptConfig("every", 1), shards=2
            ),
        )
