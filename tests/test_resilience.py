"""Unit tests for the resilience layer (ISSUE 3): RunJournal crash-safety
and invalidation, fault-injection spec parsing, retry/backoff
classification and deadlines, atomic checkpoints, and the bench's
SIGTERM flush handler.  Process-level kill/resume is covered by
tests/test_bench_resume.py; these tests pin the building blocks."""

import json
import os
import signal

import numpy as np
import pytest

from bfs_tpu.resilience.faults import (
    FaultInjected,
    corrupt_file,
    fault_point,
    fault_spec,
    reset,
)
from bfs_tpu.resilience.journal import RunJournal, config_key
from bfs_tpu.resilience.retry import (
    PermanentError,
    RetryError,
    RetryPolicy,
    TransientError,
    default_classify,
    retry_call,
)
from bfs_tpu.utils.checkpoint import (
    CheckpointError,
    latest_checkpoint,
    load_npz_strict,
    save_npz_atomic,
)

CFG = {"scale": 8, "engine": "push", "repeats": 2}


# ------------------------------------------------------------------ journal --
def test_journal_put_get_roundtrip(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    assert jr.get("reference") is None
    jr.put("reference", {"directed_traversed": 42})
    jr.put("repeat:0", {"seconds": 0.5})
    jr.close()

    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("reference") == {"directed_traversed": 42}
    assert jr2.get("repeat:0") == {"seconds": 0.5}
    assert set(jr2.resumed_phases) == {"reference", "repeat:0"}
    jr2.close()


def test_journal_key_is_config_addressed(tmp_path):
    a = RunJournal.open_for(str(tmp_path), CFG)
    b = RunJournal.open_for(str(tmp_path), {**CFG, "repeats": 3})
    assert a.path != b.path  # any knob change -> different journal
    assert config_key(CFG) == config_key(dict(reversed(list(CFG.items()))))
    a.close(), b.close()


def test_journal_torn_tail_is_trimmed(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    jr.put("reference", {"x": 1})
    jr.put("roots", {"roots": [1, 2, 3]})
    jr.close()
    # Simulate a SIGKILL mid-append: the last record loses its newline+tail.
    with open(jr.path, "r+b") as f:
        f.truncate(os.path.getsize(jr.path) - 7)

    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("reference") == {"x": 1}
    assert jr2.get("roots") is None  # torn record reads as not-completed
    jr2.put("roots", {"roots": [4]})  # and can be re-recorded cleanly
    jr2.close()
    jr3 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr3.get("roots") == {"roots": [4]}
    jr3.close()


def test_journal_crc_rejects_tampered_record(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    jr.put("reference", {"directed_traversed": 42})
    jr.put("roots", {"roots": [1]})
    jr.close()
    # Flip payload bytes of the "reference" line without touching its crc.
    lines = open(jr.path, "rb").read().splitlines(keepends=True)
    lines[1] = lines[1].replace(b"42", b"43")
    with open(jr.path, "wb") as f:
        f.writelines(lines)

    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    # The tampered record AND everything after it are distrusted.
    assert jr2.get("reference") is None
    assert jr2.get("roots") is None
    jr2.close()


def test_journal_malformed_but_parseable_records_trim_not_crash(tmp_path):
    # Valid JSON that is not a record (a byte flip landing in a key name,
    # a non-object line) must trim the tail like a torn write — never
    # escape __init__ and wedge every future run of this config.
    for damage in (b"[1, 2, 3]\n", b'{"i": 1, "phase": 9, "payload": {}}\n'):
        jr = RunJournal.open_for(str(tmp_path), CFG)
        jr.put("reference", {"x": 1})
        jr.put("roots", {"roots": [1]})
        jr.close()
        lines = open(jr.path, "rb").read().splitlines(keepends=True)
        lines[1] = damage
        with open(jr.path, "wb") as f:
            f.writelines(lines)
        jr2 = RunJournal.open_for(str(tmp_path), CFG)  # must not raise
        assert jr2.get("reference") is None
        assert jr2.get("roots") is None
        jr2.put("reference", {"x": 2})  # and keeps working
        jr2.close()
        os.remove(jr.path)


def test_journal_foreign_prejournal_file_rotates_not_truncates(tmp_path):
    """ISSUE 11 satellite (unit form; the subprocess MULTICHIP twin is in
    test_bench_resume's slow lane): a NON-journal file at the journal
    path — the pre-journal-schema MULTICHIP_r0*.json capture shape, valid
    JSON with no record sequence — must be rotated aside as evidence,
    never truncated to zero by the torn-tail trim."""
    path = str(tmp_path / "mc.jsonl")
    legacy = (
        '{"n_devices": 8, "rc": 0, "ok": true, "skipped": false,\n'
        ' "tail": "relay legs verified\\n"}\n'
    )
    with open(path, "w") as f:
        f.write(legacy)
    jr = RunJournal(path, CFG)
    assert jr.invalidated == "foreign/pre-journal file"
    jr.put("reference", {"x": 1})  # fresh journal works
    jr.close()
    assert os.path.exists(path + ".stale.0")
    assert open(path + ".stale.0").read() == legacy  # bytes preserved
    jr2 = RunJournal(path, CFG)
    assert jr2.get("reference") == {"x": 1}
    jr2.close()


def test_journal_config_mismatch_rotates_fresh(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    jr.put("reference", {"x": 1})
    path = jr.path
    jr.close()
    # Same file path but a different config header (forced collision).
    jr2 = RunJournal(path, {**CFG, "engine": "pull"})
    assert jr2.invalidated == "config mismatch"
    assert jr2.get("reference") is None
    assert os.path.exists(path + ".stale.0")  # evidence kept, not deleted
    jr2.close()


def test_journal_restart_rotates(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    jr.put("graph", {"content_hash": "aaa"})
    jr.restart("graph-hash mismatch")
    assert jr.get("graph") is None
    jr.put("graph", {"content_hash": "bbb"})
    jr.close()
    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("graph") == {"content_hash": "bbb"}
    jr2.close()


def test_journal_refuses_concurrent_writer(tmp_path, monkeypatch):
    pytest.importorskip("fcntl")
    monkeypatch.setattr(RunJournal, "LOCK_TIMEOUT_S", 0.2)
    jr = RunJournal.open_for(str(tmp_path), CFG)
    # A second live process (here: a second open file description) with
    # the same config must fail loudly, not interleave appends.
    with pytest.raises(RuntimeError, match="locked by another"):
        RunJournal.open_for(str(tmp_path), CFG)
    jr.close()
    jr2 = RunJournal.open_for(str(tmp_path), CFG)  # released on close
    jr2.close()


def test_journal_sidecar_arrays_roundtrip_and_corruption(tmp_path):
    jr = RunJournal.open_for(str(tmp_path), CFG)
    mask = np.packbits(np.arange(64) % 3 == 0)
    jr.put("reference", {"n": 64}, arrays={"mask_packed": mask})
    jr.close()

    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    arrs = jr2.load_arrays("reference")
    np.testing.assert_array_equal(arrs["mask_packed"], mask)
    jr2.close()
    # Corrupt the sidecar: the phase must read as NOT completed (re-run),
    # never as completed-with-garbage.
    sidecar = [p for p in os.listdir(tmp_path) if p.endswith(".npz")][0]
    corrupt_file(str(tmp_path / sidecar), mode="truncate")
    jr3 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr3.get("reference") is None
    jr3.close()


@pytest.mark.parametrize("mode", ["truncate", "flip"])
def test_journal_damaged_sidecar_rotates_whole_journal(tmp_path, mode):
    """ISSUE 14 satellite: a sidecar referenced by an INTACT index row
    that fails strict validation (torn write, bit rot) rotates the whole
    journal aside — later phases that consumed those arrays can no
    longer be proven consistent, so nothing of the tainted run may
    resume — and the rotated file survives as evidence."""
    jr = RunJournal.open_for(str(tmp_path), CFG)
    mask = np.packbits(np.arange(64) % 3 == 0)
    jr.put("reference", {"n": 64}, arrays={"mask_packed": mask})
    jr.put("repeat:0", {"seconds": 1.25})  # downstream of the sidecar
    jr.close()
    sidecar = [
        p for p in os.listdir(tmp_path)
        if p.endswith(".npz") and "reference" in p
    ][0]
    corrupt_file(str(tmp_path / sidecar), mode=mode)
    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("reference") is None
    assert jr2.invalidated is not None and "sidecar" in jr2.invalidated
    # The WHOLE journal rotated: the downstream phase is gone too, and
    # the old file was moved aside, never deleted.
    assert jr2.get("repeat:0") is None
    assert any(
        p.startswith(os.path.basename(jr2.path)) and ".stale." in p
        for p in os.listdir(tmp_path)
    )
    # The fresh journal is writable and resumable as usual.
    jr2.put("reference", {"n": 64}, arrays={"mask_packed": mask})
    assert jr2.get("reference") == {"n": 64}
    jr2.close()


def test_journal_missing_sidecar_only_fails_that_phase(tmp_path):
    """A MISSING sidecar file is an incomplete write, not corruption:
    the owning phase re-runs, every other phase stays restored and the
    journal is NOT rotated."""
    jr = RunJournal.open_for(str(tmp_path), CFG)
    mask = np.packbits(np.arange(64) % 3 == 0)
    jr.put("reference", {"n": 64}, arrays={"mask_packed": mask})
    jr.put("repeat:0", {"seconds": 1.25})
    jr.close()
    sidecar = [
        p for p in os.listdir(tmp_path)
        if p.endswith(".npz") and "reference" in p
    ][0]
    os.remove(tmp_path / sidecar)
    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("reference") is None
    assert jr2.get("repeat:0") == {"seconds": 1.25}
    assert jr2.invalidated is None
    jr2.close()


# ------------------------------------------------------------------- faults --
def test_fault_spec_parsing():
    assert fault_spec("") is None
    assert fault_spec("kill:verify") == ("kill", "verify", 1)
    assert fault_spec("raise:repeat:2") == ("raise", "repeat", 2)
    assert fault_spec("phase:reference") == ("kill", "reference", 1)
    # A trailing non-positive integer is part of the NAME (nth is 1-based
    # and could never fire at 0): kill:repeat:0 targets the exact
    # boundary "repeat:0", not a vacuous nth=0.
    assert fault_spec("kill:repeat:0") == ("kill", "repeat:0", 1)
    assert fault_spec("kill:repeat:0:2") == ("kill", "repeat:0", 2)
    with pytest.raises(ValueError):
        fault_spec("explode:reference")
    with pytest.raises(ValueError):
        fault_spec("kill:")
    # delay: takes SECONDS (a float) where kill/raise take nth; a
    # non-positive or non-numeric tail is part of the phase NAME.
    assert fault_spec("delay:serve.batch") == ("delay", "serve.batch", 1.0)
    assert fault_spec("delay:serve.batch:2.5") == ("delay", "serve.batch", 2.5)
    assert fault_spec("delay:repeat:0") == ("delay", "repeat:0", 1.0)
    with pytest.raises(ValueError):
        fault_spec("delay:")


def test_fault_point_delay_sleeps_every_arrival(monkeypatch):
    import time as _time

    monkeypatch.setenv("BFS_TPU_FAULT", "delay:serve.batch:0.05")
    reset()
    t0 = _time.monotonic()
    fault_point("serve.batch")
    fault_point("serve.batch")  # EVERY arrival sleeps, not just the nth
    assert _time.monotonic() - t0 >= 0.1
    t0 = _time.monotonic()
    fault_point("serve.verify")  # other boundaries unaffected
    assert _time.monotonic() - t0 < 0.05
    reset()


def test_fault_point_raise_nth(monkeypatch):
    monkeypatch.setenv("BFS_TPU_FAULT", "raise:repeat:2")
    reset()
    fault_point("repeat:0")  # first arrival in the family: no fault
    with pytest.raises(FaultInjected):
        fault_point("repeat:1")  # second arrival: boom
    fault_point("repeat:2")  # nth is exact, not at-least
    reset()


def test_fault_point_inert_without_env(monkeypatch):
    monkeypatch.delenv("BFS_TPU_FAULT", raising=False)
    reset()
    for _ in range(3):
        fault_point("verify:0")


def test_corrupt_file_modes(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(b"x" * 100)
    corrupt_file(str(p), mode="truncate")
    assert p.stat().st_size == 50
    before = p.read_bytes()
    corrupt_file(str(p), mode="flip", at=10)
    after = p.read_bytes()
    assert before[10] != after[10] and len(after) == 50


# -------------------------------------------------------------------- retry --
def test_retry_transient_then_success():
    calls = {"n": 0}
    retried = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("tunnel hiccup")
        return "ok"

    out = retry_call(
        flaky,
        policy=RetryPolicy(max_attempts=5, base_delay_s=0.0, jitter=0.0),
        on_retry=lambda a, e, d: retried.append(a),
    )
    assert out == "ok" and calls["n"] == 3 and retried == [1, 2]


def test_retry_permanent_raises_immediately():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("shape mismatch")  # classified permanent

    with pytest.raises(ValueError):
        retry_call(broken, policy=RetryPolicy(max_attempts=5, base_delay_s=0.0))
    assert calls["n"] == 1


def test_retry_exhaustion_raises_retry_error():
    def always():
        raise TransientError("still down")

    with pytest.raises(RetryError) as ei:
        retry_call(
            always,
            policy=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0),
        )
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, TransientError)


def test_retry_respects_deadline():
    import time as _time

    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise TransientError("down")

    t0 = _time.monotonic()
    with pytest.raises(RetryError):
        retry_call(
            always,
            policy=RetryPolicy(max_attempts=100, base_delay_s=0.05, jitter=0.0),
            deadline_s=0.12,
        )
    # Bounded by the deadline, not the 100 attempts.
    assert _time.monotonic() - t0 < 2.0
    assert calls["n"] < 100


def test_retry_jitter_stays_within_cap():
    import random

    policy = RetryPolicy(
        max_attempts=8, base_delay_s=0.05, max_delay_s=0.4, multiplier=2.0,
        jitter=0.5,
    )
    rng = random.Random(123)
    for attempt in range(1, 20):
        base = min(0.05 * 2.0 ** (attempt - 1), 0.4)
        for _ in range(50):
            d = policy.delay(attempt, rng)
            # Jitter is multiplicative ABOVE the backoff value: never
            # below the deterministic delay, never past the (1 + jitter)
            # factor over the capped exponential.
            assert base <= d <= base * 1.5 + 1e-12


def test_retry_delay_sleeps_never_exceed_deadline():
    """The retry loop's SLEEPS are clipped to the remaining deadline: a
    serving tick with 120 ms left must not sleep a full 500 ms backoff to
    find out the device is still down."""
    import time as _time

    slept = []  # (requested seconds, remaining deadline when requested)
    real_sleep = _time.sleep
    deadline_s = 0.12
    t0 = _time.monotonic()

    def spy_sleep(s):
        slept.append((s, deadline_s - (_time.monotonic() - t0)))
        real_sleep(min(s, 0.01))  # keep the test fast; bound is on args

    with pytest.raises(RetryError):
        with pytest.MonkeyPatch.context() as mp:
            mp.setattr("bfs_tpu.resilience.retry.time.sleep", spy_sleep)

            def always():
                raise TransientError("down")

            retry_call(
                always,
                policy=RetryPolicy(
                    max_attempts=100, base_delay_s=0.5, max_delay_s=2.0,
                    jitter=0.5,
                ),
                deadline_s=deadline_s,
            )
    assert slept, "a transient failure with attempts left must back off"
    # Every requested sleep was clipped to the wall clock REMAINING on the
    # deadline when it was computed — the full 0.5 s+ backoff never made
    # it through with only 0.12 s of budget.  The spy re-reads the clock
    # AFTER retry_call computed the clip, so a few ms of scheduler /
    # on_retry overhead sits between the two reads on a contended box —
    # the tolerance absorbs that without letting a full backoff through.
    for s, remaining in slept:
        assert s <= max(remaining, 0) + 0.05
        assert s <= deadline_s


def test_retry_policy_deadline_tighter_of_two():
    """retry_call takes the TIGHTER of policy.deadline_s and the explicit
    deadline_s argument (a request deadline must never be outlived by a
    generous policy default, and vice versa)."""
    import time as _time

    for policy_deadline, call_deadline in ((5.0, 0.1), (0.1, 5.0)):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise TransientError("down")

        t0 = _time.monotonic()
        with pytest.raises(RetryError):
            retry_call(
                always,
                policy=RetryPolicy(
                    max_attempts=1000, base_delay_s=0.02, jitter=0.0,
                    deadline_s=policy_deadline,
                ),
                deadline_s=call_deadline,
            )
        assert _time.monotonic() - t0 < 1.0  # bounded by the 0.1 s limit
        assert calls["n"] < 1000


def test_default_classify_unknown_exception_is_permanent():
    """An exception type AND message the classifier has never heard of
    defaults to permanent — an unknown failure repeated is two failures,
    not a recovery strategy."""

    class WeirdVendorError(Exception):
        pass

    assert default_classify(WeirdVendorError("status 0x7f")) == "permanent"
    assert default_classify(ArithmeticError("div")) == "permanent"
    # ...unless the unknown type's MESSAGE carries a transient marker.
    assert default_classify(WeirdVendorError("socket closed")) == "transient"


def test_default_classify():
    assert default_classify(TransientError("x")) == "transient"
    assert default_classify(PermanentError("x")) == "permanent"
    assert default_classify(ConnectionResetError()) == "transient"
    assert default_classify(TimeoutError()) == "transient"
    assert default_classify(RuntimeError("backend UNAVAILABLE: retry")) == "transient"
    assert default_classify(RuntimeError("tunnel write failed")) == "transient"
    assert default_classify(ValueError("bad shape")) == "permanent"
    assert default_classify(MemoryError()) == "permanent"


# -------------------------------------------------------------- checkpoints --
def test_save_npz_atomic_no_tmp_left(tmp_path):
    p = save_npz_atomic(tmp_path / "ck", a=np.arange(5))
    assert p.endswith(".npz") and os.path.exists(p)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    z = load_npz_strict(p)
    np.testing.assert_array_equal(z["a"], np.arange(5))


def test_load_npz_strict_rejects_truncation(tmp_path):
    p = save_npz_atomic(tmp_path / "ck", a=np.arange(1000))
    corrupt_file(p, mode="truncate")
    with pytest.raises(CheckpointError, match="truncated or corrupt"):
        load_npz_strict(p)
    with pytest.raises(FileNotFoundError):
        load_npz_strict(tmp_path / "missing.npz")


def test_load_latest_checkpoint_rejects_foreign_config(tmp_path):
    from bfs_tpu.graph.generators import gnm_graph
    from bfs_tpu.models.bfs import SuperstepRunner
    from bfs_tpu.utils.checkpoint import load_latest_checkpoint, save_checkpoint

    g = gnm_graph(40, 90, seed=3)
    runner = SuperstepRunner(g)
    state = runner.step(runner.init(0))
    base = str(tmp_path / "g.txt")
    save_checkpoint(f"{base}.ckpt_1.npz", state, source=0, engine="push")

    # Matching config resumes; a different source/engine is refused (it
    # would burn the whole tail before dying at the final check).
    assert load_latest_checkpoint(base, expect={"source": 0, "engine": "push"})
    assert (
        load_latest_checkpoint(base, expect={"source": 5, "engine": "push"})
        is None
    )
    assert (
        load_latest_checkpoint(base, expect={"source": 0, "engine": "pull"})
        is None
    )
    # Pre-metadata checkpoints (no meta_ fields) stay loadable.
    save_checkpoint(f"{base}.ckpt_2.npz", state)
    assert load_latest_checkpoint(base, expect={"source": 5})


def test_latest_checkpoint_skips_corrupt(tmp_path):
    base = str(tmp_path / "mediumG.txt")
    for level in (2, 4, 6):
        save_npz_atomic(
            f"{base}.ckpt_{level}.npz",
            dist=np.full(8, level, np.int32),
            parent=np.full(8, -1, np.int32),
            frontier=np.zeros(8, bool),
            level=np.int32(level),
            changed=np.bool_(True),
        )
    corrupt_file(f"{base}.ckpt_6.npz", mode="truncate")
    found = latest_checkpoint(base)
    assert found is not None
    path, level = found
    assert level == 4 and path.endswith(".ckpt_4.npz")
    assert latest_checkpoint(str(tmp_path / "nothing")) is None


# ------------------------------------------------------------ bench handler --
def test_bench_sigterm_handler_flushes_partial(tmp_path, capsys):
    from bfs_tpu import bench

    jr = RunJournal.open_for(str(tmp_path), CFG)
    emitted, exits = [], []
    old = bench._PARTIAL.get("emit")
    try:
        bench._PARTIAL["emit"] = lambda status: emitted.append(status)
        handler = bench._install_signal_handlers(jr, _exit=exits.append)
        handler(signal.SIGTERM, None)
    finally:
        bench._PARTIAL["emit"] = old
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGALRM, signal.SIG_DFL)
    assert exits == [128 + signal.SIGTERM]
    assert emitted and "interrupted (SIGTERM)" in emitted[0]
    # The journal tail records the interruption durably.
    jr2 = RunJournal.open_for(str(tmp_path), CFG)
    assert jr2.get("interrupted")["signal"] == "SIGTERM"
    jr2.close()
