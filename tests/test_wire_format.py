"""Vertex/Color wire-format tests: parity with ``id|[n]|[p]|dist|COLOR``
(Vertex.java:51-64,122-125) and the GraphFileUtil iteration-0 file
(GraphFileUtil.java:50-56)."""

import numpy as np
import pytest

from bfs_tpu.graph.csr import INF_DIST
from bfs_tpu.graph.vertex import (
    Color,
    Vertex,
    colors_from_state,
    initial_state_vertices,
    parse_state,
    path_to,
    serialize_state,
    state_to_vertices,
)


def test_color_ordinals_locked():
    # Ordinal order is load-bearing (Color.java:6 "DO NOT RE-ORDER",
    # BfsSpark.java:103 darkest-color merge).
    assert [c.value for c in (Color.WHITE, Color.GRAY, Color.BLACK)] == [0, 1, 2]
    assert max(Color.GRAY, Color.BLACK) == Color.BLACK


def test_serialize_format_exact():
    v = Vertex(2, (0, 1, 3, 4), (0, 2), 1, Color.GRAY)
    assert v.serialize() == "2|[0, 1, 3, 4]|[0, 2]|1|GRAY"
    w = Vertex(4, (), (0,), INF_DIST, Color.WHITE)
    assert w.serialize() == "4|[]|[0]|2147483647|WHITE"


def test_parse_roundtrip():
    line = "3|[2, 4, 5]|[0, 2, 3]|2|BLACK"
    v = Vertex.parse(line)
    assert v.id == 3 and v.distance == 2 and v.color is Color.BLACK
    assert v.neighbours == (2, 4, 5) and v.path == (0, 2, 3)
    assert v.serialize() == line


def test_parse_tolerates_no_spaces_and_empty():
    v = Vertex.parse("7|[1,2]|[]|2147483647|WHITE")
    assert v.neighbours == (1, 2) and v.path == ()


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        Vertex.parse("1|[2]|[0]|3")  # missing color field
    with pytest.raises(ValueError):
        Vertex.parse("1|2|[0]|3|GRAY")  # unbracketed list
    with pytest.raises(KeyError):
        Vertex.parse("1|[2]|[0]|3|PURPLE")


def test_with_color():
    v = Vertex(1, (0,), (0, 1), 1, Color.GRAY)
    assert v.with_color(Color.BLACK).color is Color.BLACK


def test_initial_state_vertices(tiny_graph):
    lines = [v.serialize() for v in initial_state_vertices(tiny_graph, 0)]
    # GraphFileUtil.java:50-56: source GRAY/0/path [0]; others WHITE/MAX
    # with the shared [0] path quirk (GraphFileUtil.java:55).
    assert lines[0] == "0|[1, 2, 5]|[0]|0|GRAY"
    assert lines[4] == "4|[2, 3]|[0]|2147483647|WHITE"


def test_colors_from_state():
    dist = np.array([0, 1, INF_DIST])
    frontier = np.array([False, True, False])
    assert colors_from_state(dist, frontier).tolist() == [
        int(Color.BLACK),
        int(Color.GRAY),
        int(Color.WHITE),
    ]


def test_path_to_walks_parents():
    parent = np.array([0, 0, 1, 2])
    assert path_to(parent, 3) == [0, 1, 2, 3]
    assert path_to(np.array([0, -1]), 1) == []


def test_state_roundtrip(tiny_graph):
    from bfs_tpu.models.bfs import bfs

    res = bfs(tiny_graph, 0)
    frontier = np.zeros(6, dtype=bool)
    text = serialize_state(tiny_graph, res.dist, res.parent, frontier, source=0)
    dist, parent, fr = parse_state(text, 6)
    np.testing.assert_array_equal(dist, res.dist)
    np.testing.assert_array_equal(parent, res.parent)
    assert not fr.any()
