#!/usr/bin/env python
"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).  TEPS here = directed edge count / median fused-BFS wall time,
loop fully on-device (compile excluded, like the paper excludes Spark
startup).

Env knobs: BENCH_SCALE (default 22), BENCH_EDGE_FACTOR (16), BENCH_REPEATS (5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.graph.csr import Graph, build_device_graph, DeviceGraph
from bfs_tpu.graph.ell import build_pull_graph
from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.models.bfs import _bfs_fused, _bfs_pull_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)


def load_or_build(scale: int, edge_factor: int, seed: int, block: int):
    """Device-ready R-MAT arrays, cached on disk: host-side generation +
    dst-sorting of ~10^8 edges takes minutes in NumPy, so the prepared
    DeviceGraph (and the chosen source) is built once per config.  Uses the
    native generator/sorter (native/graph_gen.cpp) when available."""
    try:
        from bfs_tpu.graph.native_gen import native_available, rmat_edges_native

        use_native = native_available()
    except Exception:
        use_native = False
    backend = "native" if use_native else "numpy"
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
    key = f"rmat_{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}"
    path = os.path.join(cache_dir, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                return (
                    DeviceGraph(
                        num_vertices=int(z["num_vertices"]),
                        num_edges=int(z["num_edges"]),
                        src=z["src"],
                        dst=z["dst"],
                    ),
                    int(z["source"]),
                )
        except Exception:
            os.remove(path)  # corrupt cache entry: rebuild below
    if use_native:
        u, v = rmat_edges_native(scale, edge_factor, seed=seed)
        graph = Graph(
            1 << scale, np.concatenate([u, v]), np.concatenate([v, u])
        )  # bi-directed (GraphFileUtil.java:64-65 parity)
    else:
        graph = rmat_graph(scale, edge_factor, seed=seed)
    dg = build_device_graph(graph, block=block)
    # Deterministic source inside the giant component: the max-degree vertex.
    degrees = np.bincount(graph.src, minlength=graph.num_vertices)
    source = int(np.argmax(degrees))
    os.makedirs(cache_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"  # unique per process: no interleaving
    np.savez(
        tmp,
        num_vertices=dg.num_vertices,
        num_edges=dg.num_edges,
        src=dg.src,
        dst=dg.dst,
        source=source,
    )
    os.replace(tmp, path)
    return dg, source


def load_or_build_pull(dg, scale: int, edge_factor: int):
    """ELL pull layout, cached next to the DeviceGraph cache (the _group_rows
    packing re-walks all E edges in NumPy — minutes at scale 22)."""
    from bfs_tpu.graph.ell import DEFAULT_K, PullGraph

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
    path = os.path.join(cache_dir, f"pull_s{scale}_ef{edge_factor}_k{DEFAULT_K}.npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                nf = int(z["num_folds"])
                return PullGraph(
                    num_vertices=int(z["num_vertices"]),
                    num_edges=int(z["num_edges"]),
                    ell0=z["ell0"],
                    folds=tuple(z[f"fold{i}"] for i in range(nf)),
                )
        except Exception:
            os.remove(path)
    pg = build_pull_graph(dg)
    os.makedirs(cache_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(
        tmp,
        num_vertices=pg.num_vertices,
        num_edges=pg.num_edges,
        ell0=pg.ell0,
        num_folds=len(pg.folds),
        **{f"fold{i}": f for i, f in enumerate(pg.folds)},
    )
    os.replace(tmp, path)
    return pg


def main():
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))
    engine = os.environ.get("BENCH_ENGINE", "pull")

    dg, source = load_or_build(scale, edge_factor, seed=42, block=8 * 1024)

    if engine == "pull":
        pg = load_or_build_pull(dg, scale, edge_factor)
        ell0 = jnp.asarray(pg.ell0)
        folds = tuple(jnp.asarray(f) for f in pg.folds)
        run = lambda: _bfs_pull_fused(  # noqa: E731
            ell0, folds, jnp.int32(source), pg.num_vertices, pg.num_vertices
        )
    else:
        src = jnp.asarray(dg.src)
        dst = jnp.asarray(dg.dst)
        run = lambda: _bfs_fused(  # noqa: E731
            src, dst, jnp.int32(source), dg.num_vertices, dg.num_vertices
        )

    state = run()  # warm-up: compile + first run
    levels = int(state.level)  # forces a real sync (block_until_ready can
    # return early through remote-device tunnels; value reads cannot)
    reached = int((np.asarray(state.dist[: dg.num_vertices]) != np.iinfo(np.int32).max).sum())

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _ = int(run().level)
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    teps = dg.num_edges / t

    print(
        json.dumps(
            {
                "metric": f"rmat{scale}_ssbfs_teps",
                "value": teps,
                "unit": "TEPS",
                "vs_baseline": teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "engine": engine,
                    "num_vertices": dg.num_vertices,
                    "num_directed_edges": dg.num_edges,
                    "source": source,
                    "supersteps": levels,
                    "vertices_reached": reached,
                    "median_seconds": t,
                    "times": times,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
