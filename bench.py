#!/usr/bin/env python
"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).  TEPS here = directed edge count / median fused-BFS wall time,
loop fully on-device (compile excluded, like the paper excludes Spark
startup).

Env knobs: BENCH_SCALE (default 22), BENCH_EDGE_FACTOR (16), BENCH_REPEATS (5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# Persistent XLA compile cache: the relay engine's ~100-stage programs take
# minutes to compile through the remote compile service; cache across runs.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache", "xla"),
)

import jax

jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
import jax.numpy as jnp
import numpy as np

from bfs_tpu.graph.csr import Graph, build_device_graph, DeviceGraph
from bfs_tpu.graph.ell import build_pull_graph
from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.models.bfs import _bfs_fused, _bfs_pull_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)


_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")


def _cached(key: str, unpack, build):
    """Load-or-rebuild an npz cache entry.  ``unpack(npz) -> obj``;
    ``build() -> (obj, dict_of_arrays)``.  Corrupt entries are treated as
    misses; writes are atomic and per-process to survive concurrent runs."""
    path = os.path.join(_CACHE_DIR, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                return unpack(z)
        except Exception:
            # Corrupt/stale entry: treat as a miss.  A concurrent process
            # may have removed it first; that's fine.
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
    obj, arrays = build()
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    return obj


def _generator_backend() -> str:
    try:
        from bfs_tpu.graph.native_gen import native_available

        return "native" if native_available() else "numpy"
    except Exception:
        return "numpy"


def load_or_build(scale: int, edge_factor: int, seed: int, block: int, backend: str):
    """Device-ready R-MAT arrays, cached on disk: host-side generation +
    dst-sorting of ~10^8 edges takes minutes in NumPy, so the prepared
    DeviceGraph (and the chosen source) is built once per config.  Uses the
    native generator/sorter (native/graph_gen.cpp) when available."""

    def unpack(z):
        return (
            DeviceGraph(
                num_vertices=int(z["num_vertices"]),
                num_edges=int(z["num_edges"]),
                src=z["src"],
                dst=z["dst"],
            ),
            int(z["source"]),
        )

    def build():
        if backend == "native":
            from bfs_tpu.graph.native_gen import rmat_edges_native

            u, v = rmat_edges_native(scale, edge_factor, seed=seed)
            graph = Graph(
                1 << scale, np.concatenate([u, v]), np.concatenate([v, u])
            )  # bi-directed (GraphFileUtil.java:64-65 parity)
        else:
            graph = rmat_graph(scale, edge_factor, seed=seed)
        dg = build_device_graph(graph, block=block)
        # Deterministic source in the giant component: the max-degree vertex.
        degrees = np.bincount(graph.src, minlength=graph.num_vertices)
        source = int(np.argmax(degrees))
        arrays = dict(
            num_vertices=dg.num_vertices,
            num_edges=dg.num_edges,
            src=dg.src,
            dst=dg.dst,
            source=source,
        )
        return (dg, source), arrays

    return _cached(
        f"rmat_{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}",
        unpack,
        build,
    )


def load_or_build_pull(dg, key: str):
    """ELL pull layout, cached next to the DeviceGraph cache (the _group_rows
    packing re-walks all E edges in NumPy — minutes at scale 22)."""
    from bfs_tpu.graph.ell import DEFAULT_K, PullGraph

    def unpack(z):
        nf = int(z["num_folds"])
        return PullGraph(
            num_vertices=int(z["num_vertices"]),
            num_edges=int(z["num_edges"]),
            ell0=z["ell0"],
            folds=tuple(z[f"fold{i}"] for i in range(nf)),
        )

    def build():
        pg = build_pull_graph(dg)
        arrays = dict(
            num_vertices=pg.num_vertices,
            num_edges=pg.num_edges,
            ell0=pg.ell0,
            num_folds=len(pg.folds),
            **{f"fold{i}": f for i, f in enumerate(pg.folds)},
        )
        return pg, arrays

    return _cached(f"pull_{key}_k{DEFAULT_K}", unpack, build)


def load_or_build_relay(dg, key: str):
    """Relay layout (relabeling + Beneš networks), cached on disk — the
    router walks ~N log N pointers host-side (minutes at scale 22, once)."""
    from bfs_tpu.graph.relay import ClassSlice, RelayGraph, build_relay_graph

    def unpack(z):
        return RelayGraph(
            num_vertices=int(z["num_vertices"]),
            num_edges=int(z["num_edges"]),
            new2old=z["new2old"],
            old2new=z["old2new"],
            vperm_masks=z["vperm_masks"],
            vperm_size=int(z["vperm_size"]),
            out_classes=tuple(
                ClassSlice(*row[:5], vertex_major=bool(row[5]))
                for row in z["out_classes"].tolist()
            ),
            net_masks=z["net_masks"],
            net_size=int(z["net_size"]),
            m2=int(z["m2"]),
            in_classes=tuple(
                ClassSlice(*row[:5], vertex_major=bool(row[5]))
                for row in z["in_classes"].tolist()
            ),
            src_l1=z["src_l1"],
        )

    def build():
        rg = build_relay_graph(dg)
        arrays = dict(
            num_vertices=rg.num_vertices,
            num_edges=rg.num_edges,
            new2old=rg.new2old,
            old2new=rg.old2new,
            vperm_masks=rg.vperm_masks,
            vperm_size=rg.vperm_size,
            out_classes=np.array(
                [[c.width, c.va, c.vb, c.sa, c.sb, int(c.vertex_major)]
                 for c in rg.out_classes],
                dtype=np.int64,
            ),
            net_masks=rg.net_masks,
            net_size=rg.net_size,
            m2=rg.m2,
            in_classes=np.array(
                [[c.width, c.va, c.vb, c.sa, c.sb, int(c.vertex_major)]
                 for c in rg.in_classes],
                dtype=np.int64,
            ),
            src_l1=rg.src_l1,
        )
        return rg, arrays

    from bfs_tpu.graph.relay import LAYOUT_VERSION

    return _cached(f"relay_v{LAYOUT_VERSION}_{key}", unpack, build)


def main():
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))
    engine = os.environ.get("BENCH_ENGINE", "relay")
    if engine not in ("relay", "pull", "push"):
        raise SystemExit(f"unknown BENCH_ENGINE {engine!r}; use relay/pull/push")

    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    graph_key = f"{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}"
    dg, source = load_or_build(scale, edge_factor, seed, block, backend)

    if engine == "relay":
        from bfs_tpu.models.bfs import RelayEngine

        rg = load_or_build_relay(dg, graph_key)
        eng = RelayEngine(rg)
        source_new = jnp.int32(int(rg.old2new[source]))
        run = lambda: eng._fused(source_new, rg.num_vertices)  # noqa: E731
    elif engine == "pull":
        pg = load_or_build_pull(dg, graph_key)
        ell0 = jnp.asarray(pg.ell0)
        folds = tuple(jnp.asarray(f) for f in pg.folds)
        run = lambda: _bfs_pull_fused(  # noqa: E731
            ell0, folds, jnp.int32(source), pg.num_vertices, pg.num_vertices
        )
    else:
        src = jnp.asarray(dg.src)
        dst = jnp.asarray(dg.dst)
        run = lambda: _bfs_fused(  # noqa: E731
            src, dst, jnp.int32(source), dg.num_vertices, dg.num_vertices
        )

    state = run()  # warm-up: compile + first run
    levels = int(state.level)  # forces a real sync (block_until_ready can
    # return early through remote-device tunnels; value reads cannot)
    reached = int((np.asarray(state.dist[: dg.num_vertices]) != np.iinfo(np.int32).max).sum())

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _ = int(run().level)
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    teps = dg.num_edges / t

    print(
        json.dumps(
            {
                "metric": f"rmat{scale}_ssbfs_teps",
                "value": teps,
                "unit": "TEPS",
                "vs_baseline": teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "engine": engine,
                    "num_vertices": dg.num_vertices,
                    "num_directed_edges": dg.num_edges,
                    "source": source,
                    "supersteps": levels,
                    "vertices_reached": reached,
                    "median_seconds": t,
                    "times": times,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
