#!/usr/bin/env python
"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).  TEPS here = directed edge count / median fused-BFS wall time,
loop fully on-device (compile excluded, like the paper excludes Spark
startup).

Env knobs: BENCH_SCALE (default 22), BENCH_EDGE_FACTOR (16), BENCH_REPEATS (5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.graph.csr import build_device_graph
from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.models.bfs import _bfs_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)


def main():
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))

    graph = rmat_graph(scale, edge_factor, seed=42)
    dg = build_device_graph(graph, block=8 * 1024)
    # Deterministic source inside the giant component: the max-degree vertex.
    degrees = np.bincount(graph.src, minlength=graph.num_vertices)
    source = int(np.argmax(degrees))

    src = jnp.asarray(dg.src)
    dst = jnp.asarray(dg.dst)
    args = (src, dst, jnp.int32(source), dg.num_vertices, dg.num_vertices)

    state = _bfs_fused(*args)  # warm-up: compile + first run
    jax.block_until_ready(state)
    levels = int(state.level)
    reached = int((np.asarray(state.dist[: dg.num_vertices]) != np.iinfo(np.int32).max).sum())

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(_bfs_fused(*args))
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    teps = graph.num_edges / t

    print(
        json.dumps(
            {
                "metric": f"rmat{scale}_ssbfs_teps",
                "value": teps,
                "unit": "TEPS",
                "vs_baseline": teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "num_vertices": graph.num_vertices,
                    "num_directed_edges": graph.num_edges,
                    "source": source,
                    "supersteps": levels,
                    "vertices_reached": reached,
                    "median_seconds": t,
                    "times": times,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
