#!/usr/bin/env python
"""Headline benchmark entry point — delegates to :mod:`bfs_tpu.bench`.

Run as ``python bench.py`` from the repo root (sys.path[0] is then the repo
root, so no path manipulation is needed) or via the installed
``bfs-tpu-bench`` console script (pyproject.toml).
"""

from bfs_tpu.bench import main

if __name__ == "__main__":
    main()
