#!/usr/bin/env python
"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).  TEPS here = directed edge count / median fused-BFS wall time,
loop fully on-device (compile excluded, like the paper excludes Spark
startup).

Env knobs: BENCH_SCALE (default 22), BENCH_EDGE_FACTOR (16), BENCH_REPEATS (5).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.graph.csr import Graph, build_device_graph, DeviceGraph
from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.models.bfs import _bfs_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)


def load_or_build(scale: int, edge_factor: int, seed: int, block: int):
    """Device-ready R-MAT arrays, cached on disk: host-side generation +
    dst-sorting of ~10^8 edges takes minutes in NumPy, so the prepared
    DeviceGraph (and the chosen source) is built once per config.  Uses the
    native generator/sorter (native/graph_gen.cpp) when available."""
    try:
        from bfs_tpu.graph.native_gen import native_available, rmat_edges_native

        use_native = native_available()
    except Exception:
        use_native = False
    backend = "native" if use_native else "numpy"
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
    key = f"rmat_{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}"
    path = os.path.join(cache_dir, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                return (
                    DeviceGraph(
                        num_vertices=int(z["num_vertices"]),
                        num_edges=int(z["num_edges"]),
                        src=z["src"],
                        dst=z["dst"],
                    ),
                    int(z["source"]),
                )
        except Exception:
            os.remove(path)  # corrupt cache entry: rebuild below
    if use_native:
        u, v = rmat_edges_native(scale, edge_factor, seed=seed)
        graph = Graph(
            1 << scale, np.concatenate([u, v]), np.concatenate([v, u])
        )  # bi-directed (GraphFileUtil.java:64-65 parity)
    else:
        graph = rmat_graph(scale, edge_factor, seed=seed)
    dg = build_device_graph(graph, block=block)
    # Deterministic source inside the giant component: the max-degree vertex.
    degrees = np.bincount(graph.src, minlength=graph.num_vertices)
    source = int(np.argmax(degrees))
    os.makedirs(cache_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"  # unique per process: no interleaving
    np.savez(
        tmp,
        num_vertices=dg.num_vertices,
        num_edges=dg.num_edges,
        src=dg.src,
        dst=dg.dst,
        source=source,
    )
    os.replace(tmp, path)
    return dg, source


def main():
    scale = int(os.environ.get("BENCH_SCALE", "22"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "16"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))

    dg, source = load_or_build(scale, edge_factor, seed=42, block=8 * 1024)

    src = jnp.asarray(dg.src)
    dst = jnp.asarray(dg.dst)
    args = (src, dst, jnp.int32(source), dg.num_vertices, dg.num_vertices)

    state = _bfs_fused(*args)  # warm-up: compile + first run
    jax.block_until_ready(state)
    levels = int(state.level)
    reached = int((np.asarray(state.dist[: dg.num_vertices]) != np.iinfo(np.int32).max).sum())

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(_bfs_fused(*args))
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    teps = dg.num_edges / t

    print(
        json.dumps(
            {
                "metric": f"rmat{scale}_ssbfs_teps",
                "value": teps,
                "unit": "TEPS",
                "vs_baseline": teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "num_vertices": dg.num_vertices,
                    "num_directed_edges": dg.num_edges,
                    "source": source,
                    "supersteps": levels,
                    "vertices_reached": reached,
                    "median_seconds": t,
                    "times": times,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
